#!/usr/bin/env python
"""Bench regression gate over the BENCH_*/MULTICHIP_* trajectory.

Every round of the bench suite leaves an artifact in the repo root:
``BENCH_rNN.json`` (the suite's final JSON line — paired-median ratios,
throughputs, latencies — captured in its ``tail``) and
``MULTICHIP_rNN.json`` (the sharded-fusion ladder). This gate turns that
trajectory into CI pass/fail:

  * the LAST artifact of each family is the candidate; every earlier
    ``rc == 0`` round is history.
  * each bench row is compared against the BEST prior value for that row
    (the bench's rows are already paired medians, so best-prior is a
    stable target — machine-load noise cancels within a row, not across
    rounds).
  * a row fails only beyond its NOISE BAND: the row's full historical
    relative swing ((max - min) / median over prior rounds), floored at
    ``--floor`` (default 0.15) for rows with little history. A row whose
    history already swings 2x cannot fail on a 1.5x move — CPU CI
    benches genuinely do that — while a stable row regressing past the
    floor fails loudly.
  * rows with no prior value are reported as new, never failed: a PR
    adding a bench row must not be gated on its own round.

Direction is inferred from the row name: ``*_per_sec`` / ``*_tflops`` /
``*_acc`` / ``*_auc`` / ``*_vs_baseline`` are higher-is-better;
``*_seconds`` / ``*_ms`` / ``*overhead*`` / ``*_skew_ratio`` are
lower-is-better; anything else (config scalars like ``seq_len``) is not
gated.

Usage:
  python tools/bench_gate.py              # gate the repo trajectory
  python tools/bench_gate.py --selftest   # synthetic regression must
                                          # fail, noisy history must
                                          # pass, real trajectory must
                                          # pass (the ci.sh smoke)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))

# `"name": number` pairs anywhere in the captured tail — the artifact
# keeps only the LAST ~2000 chars of suite stdout, so the final JSON
# line is usually truncated mid-object and a structural parse would
# lose every round; the pair scan recovers the metric rows regardless
_PAIR_RE = re.compile(r'"([a-z0-9_]+)":\s*(-?\d+(?:\.\d+)?(?:[eE]-?\d+)?)')

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

_HIGHER = ("_per_sec", "_tflops", "_gbps", "_acc", "_auc",
           "_vs_baseline", "_vs_single_chip")
_LOWER = ("_seconds", "_ms", "_skew_ratio")


def direction(name: str) -> "str | None":
    """'higher' / 'lower' is better, or None for ungated scalars."""
    if "overhead" in name:
        return "lower"
    if name.endswith(_HIGHER):
        return "higher"
    if name.endswith(_LOWER):
        return "lower"
    return None


def bench_metrics(record: dict) -> dict[str, float]:
    """Numeric bench rows from one BENCH_rNN.json record."""
    out: dict[str, float] = {}
    parsed = record.get("parsed")
    if isinstance(parsed, dict):
        blob = json.dumps(parsed)
        out.update({k: float(v) for k, v in _PAIR_RE.findall(blob)})
    out.update({k: float(v)
                for k, v in _PAIR_RE.findall(record.get("tail") or "")})
    return out


# r08 split the multichip family into a realistic ladder (>=512k rows)
# plus the pre-r08 4096-row workload carried forward as
# `fused_sharded_vs_single_smallbatch`.  Ladders from BEFORE the split
# ran only the small workload, so their rows are mapped into the
# `multichip_smallbatch_*` namespace: the carried-forward workload gates
# against its full pre-split history immediately, and only the realistic
# rows — a genuinely new measurement — get the one-round NEW grace.
_SMALLBATCH_ROWS_MAX = 8192


def _ladder_rows(ladder, prefix: str, out: dict) -> None:
    for row in ladder or []:
        nd = row.get("n_devices")
        for key in ("per_chip_vs_single_chip", "rows_per_sec",
                    "shard_skew_ratio"):
            if key in row:
                out[f"{prefix}_nd{nd}_{key}"] = float(row[key])


def multichip_metrics(record: dict) -> dict[str, float]:
    """The sharded ladders flattened to per-mesh-size rows."""
    out: dict[str, float] = {}
    legacy = (record.get("rows") or 0) <= _SMALLBATCH_ROWS_MAX
    _ladder_rows(record.get("fused_sharded_vs_single"),
                 "multichip_smallbatch" if legacy else "multichip", out)
    _ladder_rows(record.get("fused_sharded_vs_single_smallbatch"),
                 "multichip_smallbatch", out)
    return out


def load_rounds(pattern: str,
                extract) -> list[tuple[str, dict[str, float]]]:
    """(name, metrics) per successful round, in round order."""
    paths = []
    for p in glob.glob(pattern):
        m = _ROUND_RE.search(os.path.basename(p))
        if m:
            paths.append((int(m.group(1)), p))
    rounds = []
    for _, p in sorted(paths):
        try:
            with open(p) as fh:
                record = json.load(fh)
        except (OSError, ValueError):
            continue
        if record.get("rc") not in (0, None) or record.get("ok") is False:
            continue
        metrics = extract(record)
        if metrics:
            rounds.append((os.path.basename(p), metrics))
    return rounds


def gate_rounds(rounds: list[tuple[str, dict[str, float]]],
                floor: float, label: str
                ) -> tuple[list[str], list[str]]:
    """(regressions, report lines) for one artifact family."""
    report: list[str] = []
    problems: list[str] = []
    if len(rounds) < 2:
        report.append(f"{label}: {len(rounds)} usable round(s) — nothing "
                      "to gate")
        return problems, report
    cand_name, cand = rounds[-1]
    history = rounds[:-1]
    report.append(f"{label}: candidate {cand_name} vs "
                  f"{len(history)} prior round(s)")
    for name in sorted(cand):
        sense = direction(name)
        if sense is None:
            continue
        prior = [m[name] for _, m in history if name in m]
        if not prior:
            report.append(f"  NEW     {name} = {cand[name]:.4g}")
            continue
        best = max(prior) if sense == "higher" else min(prior)
        if best == 0:
            continue
        med = sorted(prior)[len(prior) // 2]
        swing = ((max(prior) - min(prior)) / abs(med)) if med else 0.0
        band = max(floor, swing)
        ratio = cand[name] / best
        bad = (ratio < 1.0 - band) if sense == "higher" \
            else (ratio > 1.0 + band)
        tag = "REGRESS" if bad else "ok"
        report.append(
            f"  {tag:7s} {name}: {cand[name]:.4g} vs best {best:.4g} "
            f"(x{ratio:.3f}, band ±{band:.0%}, {sense} is better)")
        if bad:
            problems.append(
                f"{label}/{name}: {cand[name]:.4g} is x{ratio:.3f} of "
                f"best prior {best:.4g} — beyond the ±{band:.0%} noise "
                "band")
    return problems, report


def run_gate(root: str, floor: float) -> int:
    problems: list[str] = []
    for label, pattern, extract in (
            ("bench", os.path.join(root, "BENCH_r*.json"), bench_metrics),
            ("multichip", os.path.join(root, "MULTICHIP_r*.json"),
             multichip_metrics)):
        probs, report = gate_rounds(load_rounds(pattern, extract),
                                    floor, label)
        print("\n".join(report))
        problems.extend(probs)
    if problems:
        print(f"bench_gate: {len(problems)} regression(s):",
              file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print("bench_gate: trajectory OK")
    return 0


# -- selftest ----------------------------------------------------------- #

def _fake_round(path: str, metrics: dict) -> None:
    tail = json.dumps({"extra": metrics})
    with open(path, "w") as fh:
        json.dump({"n": 1, "cmd": "synthetic", "rc": 0,
                   "tail": tail, "parsed": None}, fh)


def selftest(floor: float) -> int:
    import tempfile

    checks: dict[str, bool] = {}
    with tempfile.TemporaryDirectory() as d:
        # stable history, clearly regressed candidate -> must fail
        _fake_round(os.path.join(d, "BENCH_r01.json"),
                    {"serving_p50_ms": 1.00, "gbdt_rows_per_sec": 1e6})
        _fake_round(os.path.join(d, "BENCH_r02.json"),
                    {"serving_p50_ms": 1.05, "gbdt_rows_per_sec": 1.02e6})
        _fake_round(os.path.join(d, "BENCH_r03.json"),
                    {"serving_p50_ms": 2.40, "gbdt_rows_per_sec": 0.4e6})
        rounds = load_rounds(os.path.join(d, "BENCH_r*.json"),
                             bench_metrics)
        probs, report = gate_rounds(rounds, floor, "synthetic")
        print("\n".join(report))
        checks["synthetic regression caught"] = len(probs) == 2
        checks["latency row named"] = any("serving_p50_ms" in p
                                          for p in probs)
        checks["throughput row named"] = any("gbdt_rows_per_sec" in p
                                             for p in probs)

    with tempfile.TemporaryDirectory() as d:
        # noisy history: the same 2.4 reading is INSIDE the row's
        # historical swing (0.9..3.1) -> must pass (no flaky CI reds)
        _fake_round(os.path.join(d, "BENCH_r01.json"),
                    {"serving_p50_ms": 1.0})
        _fake_round(os.path.join(d, "BENCH_r02.json"),
                    {"serving_p50_ms": 3.1})
        _fake_round(os.path.join(d, "BENCH_r03.json"),
                    {"serving_p50_ms": 0.9})
        _fake_round(os.path.join(d, "BENCH_r04.json"),
                    {"serving_p50_ms": 2.4})
        rounds = load_rounds(os.path.join(d, "BENCH_r*.json"),
                             bench_metrics)
        probs, report = gate_rounds(rounds, floor, "noisy")
        print("\n".join(report))
        checks["noisy history passes"] = not probs

    # a new row with no history must never gate
    with tempfile.TemporaryDirectory() as d:
        _fake_round(os.path.join(d, "BENCH_r01.json"),
                    {"serving_p50_ms": 1.0})
        _fake_round(os.path.join(d, "BENCH_r02.json"),
                    {"serving_p50_ms": 1.0, "profiler_overhead": 1.01})
        rounds = load_rounds(os.path.join(d, "BENCH_r*.json"),
                             bench_metrics)
        probs, report = gate_rounds(rounds, floor, "new-row")
        checks["new row reported, not gated"] = (
            not probs and any("NEW" in ln and "profiler_overhead" in ln
                              for ln in report))

    # the repo's real trajectory must pass: the gate exists to catch
    # future regressions, not to indict history
    print()
    checks["real trajectory passes"] = run_gate(ROOT, floor) == 0

    failed = [name for name, ok in checks.items() if not ok]
    if failed:
        print(f"bench_gate selftest FAILED: {failed}", file=sys.stderr)
        return 1
    print(f"bench_gate selftest OK ({len(checks)} checks)")
    return 0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=ROOT,
                    help="directory holding BENCH_*/MULTICHIP_* artifacts")
    ap.add_argument("--floor", type=float, default=0.15,
                    help="minimum per-row noise band (relative)")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic regression caught + noise passed + "
                         "real trajectory passes")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest(args.floor)
    return run_gate(args.dir, args.floor)


if __name__ == "__main__":
    raise SystemExit(main())
