#!/usr/bin/env python
"""Static lint for metric-name literals.

The registry already rejects malformed names at runtime
(observability/metrics.py METRIC_NAME_RE), but a metric on a rarely-taken
path — a breaker transition, a retry-budget exhaustion — may never be
constructed in CI, so a bad name would ship and only explode in
production. This walks every Python source under mmlspark_tpu/ plus
bench.py, extracts every string literal starting with ``mmlspark_tpu_``
(f-strings included: ``{...}`` placeholders are stripped before
validation, so ``f"mmlspark_tpu_executable_cache_{key}_total"`` checks
the static skeleton), and enforces:

  1. charset: ``^mmlspark_tpu_[a-z0-9_]+$`` — the registry's rule.
  2. unit suffix: the name must end in one of UNIT_SUFFIXES, the
     Prometheus base-unit convention (counters ``_total``, timings
     ``_seconds``, sizes ``_bytes``, plus the dimensionless ``_ratio`` /
     ``_depth`` / ``_count`` / ``_rate`` gauges this codebase uses).
  3. merge policy: every family name must resolve to a cross-replica
     merge policy via ``observability.fleet.merge_policy_for`` — a gauge
     that neither appears in GAUGE_MERGE_POLICIES nor matches a suffix
     default would silently aggregate wrong in the fleet ``/metrics``.
  4. ``_ratio`` gauges need an EXPLICIT GAUGE_MERGE_POLICIES entry, not
     just the suffix fallback: ratios split between worst-case signals
     (fusion ratio, shard skew → max) and best-case budgets (SLO budget
     remaining → min), so the author must state which one — the suffix
     default silently picking max is exactly the aggregation bug this
     lint exists to stop.
  5. ``gateway_*`` / ``autoscaler_*`` gauges need an EXPLICIT entry too:
     those series come from the DRIVER-SIDE control plane (one routing
     gateway, one autoscaler), not from replicas, so per-replica suffix
     defaults (``_count`` → sum) would multiply them by the number of
     scrape sources. Counters and ``_seconds`` histogram families are
     exempt — both genuinely sum.
  6. OpenMetrics exemplar syntax (checked against a LIVE exposition the
     lint renders from an exemplar-enabled registry, then again after a
     fleet merge): every exemplar rides a ``_bucket`` sample as
     ``# {labels} value``, its combined label-set length stays within
     ``EXEMPLAR_LABEL_SET_MAX`` (the OpenMetrics 128-char cap), the
     exposition ends with the ``# EOF`` terminator whenever exemplars
     are present, and ``fleet.parse_prometheus`` →
     ``fleet.render_families`` round-trips the text byte-identically —
     a renderer drift here would corrupt exemplars at the aggregator.
  7. profiler phase vocabulary: every ``*_seconds`` histogram the
     profiler publishes (``observability.profiler.PROFILER_SERIES``)
     must carry a ``phase`` label, and a live Profiler driven through a
     full ledger must only ever emit phase label VALUES from the fixed
     vocabulary ``observability.profiler.PHASES`` — a free-form phase
     string would mint an unbounded label set and split the attribution
     table across misspellings.

Usage: python tools/metric_lint.py    # exit 1 with a report if any fail
"""

from __future__ import annotations

import os
import re
import sys

ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
SCAN = [os.path.join(ROOT, "mmlspark_tpu"), os.path.join(ROOT, "bench.py")]

NAME_RE = re.compile(r"^mmlspark_tpu_[a-z0-9_]+$")
UNIT_SUFFIXES = ("_total", "_seconds", "_bytes", "_ratio", "_depth",
                 "_count", "_rate")
# any single- or double-quoted literal (optionally an f-string) whose
# contents begin with the namespace prefix
LITERAL_RE = re.compile(
    r"""[fF]?("mmlspark_tpu_[^"\n]*"|'mmlspark_tpu_[^'\n]*')""")
PLACEHOLDER_RE = re.compile(r"\{[^{}]*\}")

# histogram sample suffixes: `X_bucket`/`X_sum`/`X_count` literals refer
# to samples of family X, whose policy is checked under its own name
_HISTOGRAM_SAMPLE_RE = re.compile(r"_seconds(_bucket|_sum|_count)$")


def _merge_policy_for(name: str) -> "str | None":
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.fleet import merge_policy_for
    finally:
        sys.path.pop(0)
    # counters are always summable; everything else goes through the
    # gauge resolution path (histogram families end in _seconds → "last"
    # would be wrong, but histograms are identified by kind at merge
    # time and always sum — the lint only needs SOME policy to resolve)
    kind = "counter" if name.endswith("_total") else "gauge"
    return merge_policy_for(name, kind)


def _explicit_policy(name: str) -> "str | None":
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.fleet import GAUGE_MERGE_POLICIES
    finally:
        sys.path.pop(0)
    return GAUGE_MERGE_POLICIES.get(name)


def iter_sources() -> list[str]:
    paths = []
    for entry in SCAN:
        if os.path.isfile(entry):
            paths.append(entry)
            continue
        for root, _dirs, names in os.walk(entry):
            paths.extend(os.path.join(root, n) for n in names
                         if n.endswith(".py"))
    return sorted(paths)


def lint_file(path: str) -> list[str]:
    problems = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            for match in LITERAL_RE.finditer(line):
                name = PLACEHOLDER_RE.sub("x", match.group(1)[1:-1])
                where = f"{os.path.relpath(path, ROOT)}:{lineno}"
                if not NAME_RE.match(name):
                    problems.append(
                        f"{where}: {name!r} violates "
                        "^mmlspark_tpu_[a-z0-9_]+$")
                    continue
                if not name.endswith(UNIT_SUFFIXES):
                    problems.append(
                        f"{where}: {name!r} lacks a unit suffix "
                        f"({', '.join(UNIT_SUFFIXES)})")
                    continue
                base = _HISTOGRAM_SAMPLE_RE.sub("_seconds", name)
                if _merge_policy_for(base) is None:
                    problems.append(
                        f"{where}: {name!r} has no cross-replica merge "
                        "policy (add it to observability.fleet."
                        "GAUGE_MERGE_POLICIES or use a suffix with a "
                        "default)")
                    continue
                if (name.endswith("_ratio")
                        and _explicit_policy(name) is None):
                    problems.append(
                        f"{where}: ratio gauge {name!r} relies on the "
                        "suffix-default merge policy — declare max/min "
                        "intent explicitly in observability.fleet."
                        "GAUGE_MERGE_POLICIES")
                    continue
                if (name.startswith(("mmlspark_tpu_gateway_",
                                     "mmlspark_tpu_autoscaler_"))
                        and not name.endswith("_total")
                        and not base.endswith("_seconds")
                        and _explicit_policy(name) is None):
                    problems.append(
                        f"{where}: control-plane gauge {name!r} relies "
                        "on a per-replica suffix default — gateway/"
                        "autoscaler series are driver singletons; add "
                        "an explicit observability.fleet."
                        "GAUGE_MERGE_POLICIES entry")
    return problems


# -- rule 6: OpenMetrics exemplar syntax -------------------------------- #

# `name{labels} value # {exemplar-labels} exemplar-value`
_EXEMPLAR_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?P<labels>\{[^}]*\})? "
    r"(?P<value>\S+) # \{(?P<ex>[^}]*)\} (?P<ex_value>\S+)$")
_EX_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def lint_exposition(text: str, where: str = "exposition") -> list[str]:
    """Rule 6 over one rendered exposition: exemplar syntax, the
    128-char label-set cap, the `# EOF` terminator, and a byte-identical
    fleet parse -> render round trip."""
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.fleet import (parse_prometheus,
                                                      render_families)
        from mmlspark_tpu.observability.metrics import \
            EXEMPLAR_LABEL_SET_MAX
    finally:
        sys.path.pop(0)
    problems = []
    lines = text.splitlines()
    any_exemplar = False
    for lineno, line in enumerate(lines, 1):
        if " # " not in line or line.startswith("#"):
            continue
        any_exemplar = True
        m = _EXEMPLAR_LINE_RE.match(line)
        if m is None:
            problems.append(
                f"{where}:{lineno}: malformed exemplar line {line!r}")
            continue
        if "_bucket" not in m.group("name"):
            problems.append(
                f"{where}:{lineno}: exemplar on non-bucket sample "
                f"{m.group('name')!r}")
        pairs = _EX_PAIR_RE.findall(m.group("ex"))
        total = sum(len(n) + len(v) for n, v in pairs)
        if total > EXEMPLAR_LABEL_SET_MAX:
            problems.append(
                f"{where}:{lineno}: exemplar label set is {total} chars "
                f"(cap {EXEMPLAR_LABEL_SET_MAX})")
        try:
            float(m.group("ex_value"))
        except ValueError:
            problems.append(
                f"{where}:{lineno}: exemplar value "
                f"{m.group('ex_value')!r} is not a number")
    if any_exemplar and (not lines or lines[-1].strip() != "# EOF"):
        problems.append(
            f"{where}: exemplars present but no `# EOF` terminator")
    rendered = render_families(parse_prometheus(text))
    if rendered.rstrip("\n") != text.rstrip("\n"):
        problems.append(
            f"{where}: fleet parse -> render round trip is not "
            "byte-identical")
    return problems


def lint_exemplars() -> list[str]:
    """Render a live exemplar-enabled exposition (and its fleet-merged
    re-render) and run rule 6 over both."""
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.fleet import (parse_prometheus,
                                                      render_families)
        from mmlspark_tpu.observability.metrics import MetricsRegistry
    finally:
        sys.path.pop(0)
    reg = MetricsRegistry()
    h = reg.histogram("mmlspark_tpu_serving_latency_seconds", "latency",
                      labels=("server",), exemplars=True)
    h.labels(server="srv0").observe(
        0.004, exemplar={"trace_id": "ab" * 16, "route": "resident",
                         "bucket": "8"})
    h.labels(server="srv0").observe(
        2.5, exemplar={"trace_id": "cd" * 16, "route": "host"})
    text = reg.render_prometheus()
    problems = lint_exposition(text, where="registry render")
    merged = render_families(parse_prometheus(text))
    problems.extend(lint_exposition(merged, where="fleet re-render"))
    return problems


# -- rule 7: profiler phase vocabulary ---------------------------------- #


def lint_profiler_phases() -> list[str]:
    """Rule 7: the profiler's ``*_seconds`` histograms must declare the
    ``phase`` label (statically, via its PROFILER_SERIES manifest), and
    a live ledger driven through every phase must emit only label values
    from the fixed PHASES vocabulary."""
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.metrics import MetricsRegistry
        from mmlspark_tpu.observability.profiler import (PHASE_LABEL,
                                                         PHASES,
                                                         PROFILER_SERIES,
                                                         Profiler)
    finally:
        sys.path.pop(0)
    problems = []
    for name, (kind, labelnames) in sorted(PROFILER_SERIES.items()):
        if name.endswith("_seconds") and kind == "histogram" \
                and PHASE_LABEL not in labelnames:
            problems.append(
                f"profiler series {name!r} is a timing histogram without "
                f"a {PHASE_LABEL!r} label — attribution cannot group it "
                "by phase")
    # live exercise: one ledger touching every phase, then inspect the
    # actual label values the registry recorded
    reg = MetricsRegistry()
    prof = Profiler(registry=reg, enabled=True)
    led = prof.ledger("lint", "seg0")
    for ph in PHASES:
        led.add(ph, 0.001)
    led.note_pad(6, 8)
    led.note_shard("TPU_0", 0.002, rows=6)
    led.done(rtt_s=0.01)
    prof.flush()  # commits drain on a background thread
    try:
        led.add("not_a_phase", 0.001)
    except ValueError:
        pass
    else:
        problems.append(
            "PhaseLedger.add accepted a phase outside PHASES — the "
            "vocabulary is not enforced at the recording site")
    vocab = set(PHASES)
    seen_phases = 0
    for name, fam in reg.snapshot().items():
        for sample in fam.get("samples", []):
            phase = (sample.get("labels") or {}).get(PHASE_LABEL)
            if phase is None:
                continue
            seen_phases += 1
            if phase not in vocab:
                problems.append(
                    f"live profiler emitted phase label {phase!r} on "
                    f"{name!r} — outside the fixed vocabulary "
                    f"{'|'.join(PHASES)}")
    if not seen_phases:
        problems.append(
            "live profiler ledger committed no phase-labeled samples — "
            "the rule 7 dynamic check is vacuous")
    return problems


def main() -> None:
    checked = 0
    problems: list[str] = []
    for path in iter_sources():
        found = lint_file(path)
        problems.extend(found)
        with open(path) as fh:
            checked += sum(1 for line in fh
                           for _ in LITERAL_RE.finditer(line))
    problems.extend(lint_exemplars())
    problems.extend(lint_profiler_phases())
    if problems:
        print(f"metric_lint: {len(problems)} problem(s):")
        for p in problems:
            print(f"  {p}")
        raise SystemExit(1)
    print(f"metric_lint: {checked} metric-name literal(s) OK; "
          "exemplar exposition OK (rule 6); "
          "profiler phase vocabulary OK (rule 7)")


if __name__ == "__main__":
    main()
