#!/usr/bin/env python
"""Static lint for metric-name literals — now a shim over graftlint.

The seven rules that lived here (charset, unit suffix, merge-policy
resolution, explicit ``_ratio`` policies, explicit control-plane gauge
policies, OpenMetrics exemplar syntax, profiler phase vocabulary) moved
into the graftlint registry as rules M1–M7
(``tools/graftlint/rules_metrics.py``), where they run alongside the
concurrency (R1–R3) and device-hazard (R4–R6) rules under one runner,
one baseline file, and per-rule exit codes.

This entry point is kept so ``python tools/metric_lint.py`` (muscle
memory, older docs, external CI configs) still works: it runs exactly
the M rules and exits non-zero on any finding — the same contract as
before. Prefer ``python -m tools.graftlint`` for the full gate and
``python -m tools.graftlint --rules M1,M2`` for rule selection. See
docs/analysis.md for the rule catalog.
"""

from __future__ import annotations

import os
import sys

# run as a script from anywhere: put the repo root on sys.path so the
# tools package (and mmlspark_tpu next to it) resolve
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.graftlint.engine import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(
        main(["--rules", "M1,M2,M3,M4,M5,M6,M7"] + sys.argv[1:]))
