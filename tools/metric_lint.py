#!/usr/bin/env python
"""Static lint for metric-name literals.

The registry already rejects malformed names at runtime
(observability/metrics.py METRIC_NAME_RE), but a metric on a rarely-taken
path — a breaker transition, a retry-budget exhaustion — may never be
constructed in CI, so a bad name would ship and only explode in
production. This walks every Python source under mmlspark_tpu/ plus
bench.py, extracts every string literal starting with ``mmlspark_tpu_``
(f-strings included: ``{...}`` placeholders are stripped before
validation, so ``f"mmlspark_tpu_executable_cache_{key}_total"`` checks
the static skeleton), and enforces:

  1. charset: ``^mmlspark_tpu_[a-z0-9_]+$`` — the registry's rule.
  2. unit suffix: the name must end in one of UNIT_SUFFIXES, the
     Prometheus base-unit convention (counters ``_total``, timings
     ``_seconds``, sizes ``_bytes``, plus the dimensionless ``_ratio`` /
     ``_depth`` / ``_count`` gauges this codebase uses).

Usage: python tools/metric_lint.py    # exit 1 with a report if any fail
"""

from __future__ import annotations

import os
import re
import sys

ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
SCAN = [os.path.join(ROOT, "mmlspark_tpu"), os.path.join(ROOT, "bench.py")]

NAME_RE = re.compile(r"^mmlspark_tpu_[a-z0-9_]+$")
UNIT_SUFFIXES = ("_total", "_seconds", "_bytes", "_ratio", "_depth",
                 "_count")
# any single- or double-quoted literal (optionally an f-string) whose
# contents begin with the namespace prefix
LITERAL_RE = re.compile(
    r"""[fF]?("mmlspark_tpu_[^"\n]*"|'mmlspark_tpu_[^'\n]*')""")
PLACEHOLDER_RE = re.compile(r"\{[^{}]*\}")


def iter_sources() -> list[str]:
    paths = []
    for entry in SCAN:
        if os.path.isfile(entry):
            paths.append(entry)
            continue
        for root, _dirs, names in os.walk(entry):
            paths.extend(os.path.join(root, n) for n in names
                         if n.endswith(".py"))
    return sorted(paths)


def lint_file(path: str) -> list[str]:
    problems = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            for match in LITERAL_RE.finditer(line):
                name = PLACEHOLDER_RE.sub("x", match.group(1)[1:-1])
                where = f"{os.path.relpath(path, ROOT)}:{lineno}"
                if not NAME_RE.match(name):
                    problems.append(
                        f"{where}: {name!r} violates "
                        "^mmlspark_tpu_[a-z0-9_]+$")
                elif not name.endswith(UNIT_SUFFIXES):
                    problems.append(
                        f"{where}: {name!r} lacks a unit suffix "
                        f"({', '.join(UNIT_SUFFIXES)})")
    return problems


def main() -> None:
    checked = 0
    problems: list[str] = []
    for path in iter_sources():
        found = lint_file(path)
        problems.extend(found)
        with open(path) as fh:
            checked += sum(1 for line in fh
                           for _ in LITERAL_RE.finditer(line))
    if problems:
        print(f"metric_lint: {len(problems)} problem(s):")
        for p in problems:
            print(f"  {p}")
        raise SystemExit(1)
    print(f"metric_lint: {checked} metric-name literal(s) OK")


if __name__ == "__main__":
    main()
