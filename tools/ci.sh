#!/usr/bin/env bash
# CI quality gate (the reference's `runme` analogue, L8 tooling):
#   1. graftlint selftests (each rule catches its seeded violation and
#      stays quiet on a clean twin) then the full static-analysis gate:
#      concurrency (R1–R3, unsuppressable), device hazards (R4–R6,
#      baselined with justification), metric names (M1–M7). Zero
#      unsuppressed findings and zero stale baseline entries to pass.
#   2. fleet-observability smoke (2 real replicas scraped + aggregated)
#      + flight-recorder postmortem smoke (synthetic 3-process incident)
#      + distributed-streaming smoke (real P=2 partition-parallel query
#        diagnosed from its checkpoint dir)
#      + perf-attribution smoke (armed profiler on a live resident
#        server; phase sum must cover the measured RTT)
#      + training-checkpoint smoke (real store + checkpointed GBDT fit;
#        corruption fallback and lineage table assertions)
#      + sweep-ledger smoke (known AutoML sweep ledger rendered; every
#        trial state the table can show asserted)
#      + elastic-training smoke (real elastic GBDT fit with a worker
#        kill and a join mid-fit; world-epoch/member/re-shard table
#        assertions)
#      + timeline-history smoke (recorded incident: alert fires after
#        for_s on a fake clock, dump triggered, segment store replayed
#        into a byte-stable --history report)
#   3. bench regression gate over the BENCH_*/MULTICHIP_* trajectory
#   4. pipeline-fusion segment report (fails if an exemplar stops fusing)
#   5. full test suite on the 8-virtual-device CPU mesh
#   6. threaded-subsystem shard re-run under the runtime lock-order
#      sanitizer (MMLSPARK_TPU_SANITIZE=1 hard-fails on any lock-order
#      cycle or blocking-under-lock the static pass could not see)
#   7. multi-chip dryrun (sharding compiles + replicated-model check)
#   8. benchmark smoke on CPU (fail-soft backend selection)
set -euo pipefail
cd "$(dirname "$0")/.."
python -m tools.graftlint --selftest
python -m tools.graftlint
python tools/diagnose.py --selftest
python tools/diagnose.py --postmortem --selftest
python tools/diagnose.py --streaming --selftest
python tools/diagnose.py --perf --selftest
python tools/diagnose.py --checkpoints --selftest
python tools/diagnose.py --sweep --selftest
python tools/diagnose.py --training --selftest
python tools/diagnose.py --history --selftest
python tools/bench_gate.py --selftest
python tools/fusion_report.py
python -m pytest tests/ -q
MMLSPARK_TPU_SANITIZE=1 python -m pytest -q \
    tests/test_serving.py tests/test_streaming.py tests/test_io_http.py \
    tests/test_resilience.py tests/test_observability.py \
    tests/test_automl_sweep.py tests/test_elastic_fleet.py \
    tests/test_dataplane.py tests/test_sharded_fusion.py \
    tests/test_donated_pipelined.py tests/test_timeline.py
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"
MMLSPARK_TPU_BENCH_FORCE_CPU=1 python bench.py
