"""Batch-size sweep for the model-runner forward and the trainer step.

Companion to tools/sweep_hist.py (GBDT kernel sweep): run ON CHIP to pick
the throughput-optimal batch size, commit the CSV so kernel/batch choices
are grounded in measured numbers (VERDICT r2: "no sweep result is
committed, kernel choice ... never validated on hardware").

Usage:
    python tools/sweep_batch.py [--out sweeps/batch_sweep.csv]

Prints CSV: family,batch,images_per_sec,tflops,mfu
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sweep_runner(batches, peak_tflops):
    import jax
    import jax.numpy as jnp

    from bench import flops_of, flops_sane, median_timed
    from mmlspark_tpu.nn.models import ModelBundle

    bundle = ModelBundle.init("resnet20_cifar", input_shape=(32, 32, 3), seed=0)
    bf16_vars = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
        bundle.variables,
    )

    @jax.jit
    def fwd(v, xb):
        xf = (xb.astype(jnp.float32) - 127.5) / 63.75
        return bundle.module.apply(v, xf.astype(jnp.bfloat16), train=False)

    rng = np.random.default_rng(0)
    rows = []
    for bs in batches:
        n = max(bs * 8, 4096)
        images = rng.integers(0, 256, size=(n, 32, 32, 3), dtype=np.uint8)
        xd = jax.device_put(images)
        jax.block_until_ready(fwd(bf16_vars, xd[:bs]))

        def one_pass():
            outs = [fwd(bf16_vars, xd[i:i + bs]) for i in range(0, n, bs)]
            jax.block_until_ready(outs[-1])

        ips = n / median_timed(one_pass)
        fl = flops_of(fwd, bf16_vars, xd[:bs])
        per_img = flops_sane(fl / bs if fl else None, 8.2e7, "runner fwd")
        tflops = ips * per_img / 1e12
        mfu = tflops / peak_tflops if peak_tflops else float("nan")
        rows.append(("runner_fwd_bf16", bs, ips, tflops, mfu))
        print(f"runner bs={bs}: {ips:,.0f} img/s, {tflops:.2f} TFLOP/s, "
              f"mfu={mfu:.3f}", file=sys.stderr)
    return rows


def sweep_trainer(batches, peak_tflops, side=224, scan_steps=8):
    """Two dispatch patterns per batch size:

    * ``scan`` — all steps inside ONE jitted lax.scan, the DNNLearner
      fused-epoch pattern (nn/trainer.py). One dispatch per measurement.
    * ``loop`` — one dispatch per step (the naive host loop). On the
      tunneled chip this pays per-dispatch client latency every step;
      the scan/loop ratio IS the measured dispatch tax.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from bench import flops_of, flops_sane
    from mmlspark_tpu.nn.models import make_model

    module = make_model("resnet50", num_outputs=10, dtype=jnp.bfloat16)
    rng = np.random.default_rng(1)
    rows = []
    for bs in batches:
        xb = jnp.asarray(rng.integers(0, 256, size=(bs, side, side, 3),
                                      dtype=np.uint8))
        yb = jnp.asarray(rng.integers(0, 10, size=bs), jnp.int32)
        variables = module.init(jax.random.PRNGKey(0),
                                xb[:1].astype(jnp.float32))
        params, batch_stats = variables["params"], variables["batch_stats"]
        tx = optax.adam(1e-3)
        opt_state = tx.init(params)

        def step(params, batch_stats, opt_state):
            def loss_fn(p):
                logits, upd = module.apply(
                    {"params": p, "batch_stats": batch_stats},
                    xb.astype(jnp.float32), train=True,
                    mutable=["batch_stats"],
                )
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb).mean(), upd["batch_stats"]

            (loss, bst), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), bst, opt_state, loss

        fl = flops_of(jax.jit(step), params, batch_stats, opt_state)
        per_img = flops_sane(fl / bs if fl else None,
                             3 * 4.1e9 * (side / 224) ** 2, "trainer step")

        def scan_steps_fn(params, batch_stats, opt_state):
            def body(carry, _):
                p, bst, o, loss = step(*carry)
                return (p, bst, o), loss
            (p, bst, o), losses = jax.lax.scan(
                body, (params, batch_stats, opt_state), None,
                length=scan_steps)
            return p, bst, o, losses[-1]

        for name, fn, n_dispatch in (
                ("scan", jax.jit(scan_steps_fn), 1),
                ("loop", jax.jit(step, donate_argnums=(0, 1, 2)), scan_steps)):
            p, bst, o = params, batch_stats, opt_state
            if n_dispatch == 1:
                out = fn(p, bst, o)          # compile + warm
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                out = fn(p, bst, o)
                jax.block_until_ready(out)
                dt = time.perf_counter() - t0
            else:
                p, bst, o, _ = fn(p, bst, o)  # compile + warm
                t0 = time.perf_counter()
                for _ in range(scan_steps):
                    p, bst, o, loss = fn(p, bst, o)
                jax.block_until_ready(loss)
                dt = time.perf_counter() - t0
            ips = scan_steps * bs / dt
            tflops = ips * per_img / 1e12
            mfu = tflops / peak_tflops if peak_tflops else float("nan")
            rows.append((f"trainer_resnet50_{side}_{name}", bs, ips, tflops,
                         mfu))
            print(f"trainer[{name}] bs={bs}: {ips:,.0f} img/s, "
                  f"{tflops:.2f} TFLOP/s, mfu={mfu:.3f}", file=sys.stderr)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="also write CSV here")
    ap.add_argument("--runner-batches", default="256,512,1024,2048,4096")
    # 128+ excluded from the default: the 224px ResNet-50 backward compile
    # at bs=128 hung >21 min on the tunneled chip (2026-07-30 session) and
    # a native compile hang is unkillable in-process
    ap.add_argument("--trainer-batches", default="32,64")
    ap.add_argument("--trainer-side", type=int, default=224)
    args = ap.parse_args()

    import jax

    from bench import chip_peaks, pin_cpu_if_requested

    pin_cpu_if_requested()

    kind, peak_tflops, _ = chip_peaks()
    print(f"sweep on {kind} ({jax.default_backend()})", file=sys.stderr)

    rows = sweep_runner([int(b) for b in args.runner_batches.split(",")],
                        peak_tflops)
    try:
        rows += sweep_trainer([int(b) for b in args.trainer_batches.split(",")],
                              peak_tflops, side=args.trainer_side)
    except Exception as e:  # noqa: BLE001 — OOM at large batch ends the sweep
        print(f"trainer sweep stopped: {e!r}", file=sys.stderr)

    lines = ["family,batch,images_per_sec,tflops,mfu"]
    lines += [f"{f},{b},{ips:.1f},{tf:.3f},{mfu:.4f}"
              for f, b, ips, tf, mfu in rows]
    csv = "\n".join(lines)
    print(csv)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(csv + "\n")


if __name__ == "__main__":
    main()
