"""graftlint: concurrency & device-hazard static analysis.

Run with ``python -m tools.graftlint`` from the repo root. See
docs/analysis.md for the rule catalog and baseline workflow.
"""

from .engine import Finding, Rule, main, rules  # noqa: F401

__all__ = ["Finding", "Rule", "main", "rules"]
