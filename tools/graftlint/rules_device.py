"""R4 host-sync-in-hot-path, R6 recompile-hazard.

Both rules flag *costs the type system can't see*: a ``.item()`` on a
device array stalls the dispatch pipeline for a full device round-trip;
a ``jax.jit`` wrapper constructed per call throws away XLA's executable
cache and re-traces every time. Findings here are triaged — a site that
is deliberate (a terminal readback, a builder invoked once per model)
goes in the baseline WITH a one-line justification; the rule exists so
every new site forces that conversation.
"""

from __future__ import annotations

import ast

from .astinfo import Index, index_source, is_self_attr
from .engine import Finding, Rule, register

# -- R4 ------------------------------------------------------------------- #

# a function (or its class/module) is "hot" when its name advertises the
# fused/per-request path — the paths whose latency budget is microseconds
_HOT_MARKERS = ("fused", "hot", "kernel", "resident", "score")

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_SYNC_NP = {"asarray", "array"}


def _is_hot(qualname: str, relpath: str) -> bool:
    hay = f"{relpath}:{qualname}".lower()
    return any(m in hay for m in _HOT_MARKERS)


def _r4_run(idx: Index) -> "list[Finding]":
    out: list[Finding] = []
    for mod, fi in idx.all_funcs():
        if not _is_hot(fi.qualname, mod.relpath):
            continue
        for node, _held in fi.events:
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            op = None
            if isinstance(f, ast.Attribute):
                if f.attr in _SYNC_ATTRS:
                    op = f.attr
                elif (f.attr in _SYNC_NP
                      and isinstance(f.value, ast.Name)
                      and f.value.id == "np"):
                    op = f"np.{f.attr}"
            elif (isinstance(f, ast.Name) and f.id == "float"
                  and node.args
                  and isinstance(node.args[0], (ast.Call, ast.Subscript))):
                op = "float"
            if op is not None:
                out.append(Finding(
                    "R4", mod.relpath, node.lineno, fi.qualname,
                    f"sync:{op}",
                    f"{op}() forces a host-device sync inside hot-path "
                    f"function {fi.qualname} — hide it behind the "
                    "async-readback path or justify in the baseline"))
    return out


_R4_BAD = """
def hot_path_score(x):
    return x.item()
"""

_R4_CLEAN = """
def summarize(x):
    return x.item()

def hot_path_score(x):
    return x + 1
"""


# -- R6 ------------------------------------------------------------------- #

_CACHE_DECOS = {"lru_cache", "cache", "cached_property"}


def _is_jit_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    return (isinstance(f, ast.Attribute) and f.attr == "jit"
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def _deco_name(deco: ast.AST) -> "str | None":
    if isinstance(deco, ast.Call):
        deco = deco.func
    if isinstance(deco, ast.Attribute):
        return deco.attr
    if isinstance(deco, ast.Name):
        return deco.id
    return None


def _names_cache(node: ast.AST) -> bool:
    """True when an assignment target routes the value into something
    whose name admits it is a cache (``cache[key]``, ``self._jit_cache``)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return "cache" in node.attr.lower()
    if isinstance(node, ast.Name):
        return "cache" in node.id.lower()
    return False


def _r6_run(idx: Index) -> "list[Finding]":
    out: list[Finding] = []
    for mod in idx.modules:
        parents: dict = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and _is_jit_call(node)):
                continue
            chain = []
            cur = node
            while cur in parents:
                cur = parents[cur]
                chain.append(cur)
            funcs = [c for c in chain
                     if isinstance(c, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            if not funcs:
                continue                # module-level: XLA caches by id
            enclosing = funcs[0]
            qual = ".".join([c.name for c in reversed(chain)
                             if isinstance(c, (ast.FunctionDef,
                                               ast.AsyncFunctionDef,
                                               ast.ClassDef))])
            parent = parents[node]
            if isinstance(parent, ast.Call) and parent.func is node:
                out.append(Finding(
                    "R6", mod.relpath, node.lineno, qual, "jit-immediate",
                    "jax.jit(...)(...) builds a fresh jit wrapper per "
                    "call — every invocation re-traces; hoist the "
                    "wrapper or route through ExecutableCache"))
                continue
            if enclosing.name == "__init__":
                continue                # one wrapper per object lifetime
            if any(_deco_name(d) in _CACHE_DECOS
                   for d in enclosing.decorator_list):
                continue
            cls_chain = [c for c in chain if isinstance(c, ast.ClassDef)]
            if cls_chain and "cache" in cls_chain[0].name.lower():
                continue
            if isinstance(parent, ast.Assign) and any(
                    _names_cache(t) or is_self_attr(t)
                    for t in parent.targets):
                continue
            if (isinstance(parent, ast.Call)
                    and isinstance(parent.func, ast.Attribute)
                    and parent.func.attr == "get_or_build"):
                continue
            out.append(Finding(
                "R6", mod.relpath, node.lineno, qual, "jit-in-function",
                f"jax.jit constructed inside {qual} with no visible "
                "cache (ExecutableCache / lru_cache / cache-dict "
                "assignment) — recompiles unless every caller memoizes "
                "the result"))
    return out


_R6_BAD = """
import jax
def f(x):
    return jax.jit(lambda y: y + 1)(x)
"""

_R6_CLEAN = """
import functools
import jax

def _fwd(y):
    return y + 1

g = jax.jit(_fwd)

@functools.lru_cache(maxsize=8)
def build(n):
    return jax.jit(_fwd)
"""


def _fixture_selftest(run, bad: str, clean: str):
    def selftest() -> "list[str]":
        problems = []
        if not run(index_source(bad)):
            problems.append("seeded violation was NOT caught")
        leaked = run(index_source(clean))
        if leaked:
            problems.append(
                f"clean twin produced findings: "
                f"{[f.message for f in leaked]}")
        return problems
    return selftest


register(Rule(
    id="R4", title="host-sync-in-hot-path: .item()/np.asarray/"
    "block_until_ready inside fused/hot-path/kernel functions",
    run=_r4_run, selftest=_fixture_selftest(_r4_run, _R4_BAD, _R4_CLEAN)))

register(Rule(
    id="R6", title="recompile-hazard: per-call jax.jit wrappers not "
    "routed through a cache",
    run=_r6_run, selftest=_fixture_selftest(_r6_run, _R6_BAD, _R6_CLEAN)))
