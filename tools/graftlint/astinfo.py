"""AST index shared by every graftlint rule.

One parse of the repo produces, per module: the classes, their lock
attributes (``self._x = threading.Lock()`` / ``make_lock(...)``), their
thread entry points (``threading.Thread(target=self._loop)`` and
local-closure targets), constructor-based attribute types
(``self.pool = TargetPool(...)`` — the one-level cross-class link R1–R3
propagate through), and, per function, a flat event stream of
``(ast-node, lockset-held)`` pairs plus the ordered lock acquisitions.

The lockset walker is deliberately syntactic: a lock is "held" inside a
``with self._lock:`` / ``with MODULE_LOCK:`` block over an attribute or
name the index recognized as lock-typed. Nested ``def``/``lambda``
bodies are excluded from the enclosing lockset (they run later, on
whatever thread calls them); a nested function handed to
``threading.Thread(target=...)`` is indexed as its own thread-entry
function instead.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
LOCK_FACTORIES = {"make_lock", "make_rlock"}

# attribute types whose mutator methods are atomic under the GIL (CPython
# deque/queue) or are synchronization objects themselves — R1 does not
# require a lock around their method calls
SAFE_CTORS = {"deque", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
              "Event", "Semaphore", "BoundedSemaphore", "Barrier"}

MUTATORS = {"add", "append", "appendleft", "extend", "insert", "pop",
            "popleft", "popitem", "remove", "discard", "clear", "update",
            "setdefault", "__setitem__"}


def call_name(call: ast.Call) -> "str | None":
    """Last identifier of a call's function: ``a.b.c(...)`` -> ``c``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and call_name(node) in (LOCK_CTORS | LOCK_FACTORIES))


def is_self_attr(node: ast.AST) -> "str | None":
    """``self.X`` -> ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@dataclass
class FuncInfo:
    qualname: str                    # "Class.method" / "func" / "C.m.<f>"
    name: str
    node: ast.AST
    relpath: str
    cls: "ClassInfo | None" = None
    is_init: bool = False
    # (node, held-lockset) for every expression/simple-statement node
    events: list = field(default_factory=list)
    # (lock-id, held-lockset-before, lineno) in source order
    acquires: list = field(default_factory=list)

    @property
    def lineno(self) -> int:
        return self.node.lineno

    # -- derived views (cached) -------------------------------------- #

    def self_writes(self) -> "list[tuple[str, tuple, int, str]]":
        """(attr, lockset, lineno, how) for every write to ``self.X``:
        assignment, augmented assignment, ``self.X[...] = v``, or a
        mutator-method call (``self.X.append(...)``)."""
        cached = getattr(self, "_writes", None)
        if cached is not None:
            return cached
        out = []
        safe = self.cls.safe_attrs if self.cls else set()

        def tgt(node, held, lineno, how):
            attr = is_self_attr(node)
            if attr is not None:
                out.append((attr, held, lineno, how))
            elif isinstance(node, ast.Subscript):
                attr = is_self_attr(node.value)
                if attr is not None:
                    out.append((attr, held, lineno, "item"))
            elif isinstance(node, (ast.Tuple, ast.List)):
                for el in node.elts:
                    tgt(el, held, lineno, how)

        for node, held in self.events:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    tgt(t, held, node.lineno, "assign")
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if getattr(node, "value", True) is not None:
                    tgt(node.target, held, node.lineno, "assign")
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr in MUTATORS):
                    attr = is_self_attr(f.value)
                    if attr is not None and attr not in safe:
                        out.append((attr, held, node.lineno, "mutate"))
        self._writes = out
        return out

    def self_reads(self) -> "set[str]":
        """Attrs of ``self`` loaded anywhere in the function."""
        cached = getattr(self, "_reads", None)
        if cached is not None:
            return cached
        out = set()
        for node, _held in self.events:
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.ctx, ast.Load)):
                    attr = is_self_attr(sub)
                    if attr is not None:
                        out.add(attr)
        self._reads = out
        return out

    def self_calls(self) -> "list[tuple[str, tuple, int]]":
        """(method, lockset, lineno) for every ``self.m(...)`` call."""
        cached = getattr(self, "_scalls", None)
        if cached is not None:
            return cached
        out = []
        for node, held in self.events:
            if isinstance(node, ast.Call):
                attr = is_self_attr(node.func)
                if attr is not None:
                    out.append((attr, held, node.lineno))
        self._scalls = out
        return out

    def attr_calls(self) -> "list[tuple[str, str, tuple, int]]":
        """(attr, method, lockset, lineno) for ``self.X.m(...)`` calls —
        the cross-class propagation sites."""
        cached = getattr(self, "_acalls", None)
        if cached is not None:
            return cached
        out = []
        for node, held in self.events:
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                        ast.Attribute):
                attr = is_self_attr(node.func.value)
                if attr is not None:
                    out.append((attr, node.func.attr, held, node.lineno))
        self._acalls = out
        return out


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    relpath: str
    lock_attrs: set = field(default_factory=set)
    safe_attrs: set = field(default_factory=set)
    attr_types: dict = field(default_factory=dict)   # attr -> class name
    funcs: dict = field(default_factory=dict)        # name -> FuncInfo
    thread_targets: set = field(default_factory=set)  # names into funcs


@dataclass
class ModuleInfo:
    relpath: str
    path: str
    tree: ast.Module
    classes: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)
    module_locks: set = field(default_factory=set)

    @property
    def stem(self) -> str:
        return os.path.basename(self.relpath)


@dataclass
class Index:
    root: str
    modules: list
    classes_by_name: dict = field(default_factory=dict)

    def all_funcs(self):
        for mod in self.modules:
            for fn in mod.functions.values():
                yield mod, fn
            for cls in mod.classes.values():
                for fn in cls.funcs.values():
                    yield mod, fn


# -- lockset walking ------------------------------------------------------ #


def _scan_func(fninfo: FuncInfo, module: ModuleInfo) -> None:
    """Populate events + acquires for one function."""
    cls = fninfo.cls
    local_locks = set()
    for node in ast.walk(fninfo.node):
        if (isinstance(node, ast.Assign) and _is_lock_ctor(node.value)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local_locks.add(t.id)

    def lock_id(expr: ast.AST) -> "str | None":
        attr = is_self_attr(expr)
        if attr is not None and cls is not None and attr in cls.lock_attrs:
            return f"{cls.name}.{attr}"
        if isinstance(expr, ast.Name):
            if expr.id in module.module_locks:
                return f"{module.stem}:{expr.id}"
            if expr.id in local_locks:
                return f"{fninfo.qualname}:{expr.id}"
        return None

    events, acquires = fninfo.events, fninfo.acquires

    def walk(node: ast.AST, held: list) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly: list = []
            for item in node.items:
                walk(item.context_expr, held + newly)
                lid = lock_id(item.context_expr)
                if lid is not None:
                    acquires.append((lid, tuple(held + newly),
                                     item.context_expr.lineno))
                    newly.append(lid)
            for st in node.body:
                walk(st, held + newly)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return                      # different execution context
        events.append((node, tuple(held)))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    body = fninfo.node.body if hasattr(fninfo.node, "body") else []
    for st in body:
        walk(st, [])


def _thread_target_names(call: ast.Call) -> "list[ast.AST]":
    """target= expressions of a ``threading.Thread(...)`` construction."""
    if call_name(call) != "Thread":
        return []
    return [kw.value for kw in call.keywords if kw.arg == "target"]


def _index_class(node: ast.ClassDef, module: ModuleInfo) -> ClassInfo:
    cls = ClassInfo(name=node.name, node=node, relpath=module.relpath)
    methods = [st for st in node.body
               if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _ann_class(ann: "ast.AST | None") -> "str | None":
        """First class-like identifier of a parameter annotation —
        handles ``Foo``, ``"Foo | None"``, ``Optional[Foo]``."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            head = ann.value.split("|")[0].strip().split("[")[0].strip()
            return head if head.lstrip("_")[:1].isupper() else None
        if isinstance(ann, ast.Name):
            return ann.id if ann.id.lstrip("_")[:1].isupper() else None
        if isinstance(ann, ast.Subscript):
            return _ann_class(ann.slice)
        if isinstance(ann, ast.BinOp):
            return _ann_class(ann.left)
        return None

    # pass 1: locks, attr types, thread targets, nested-closure targets
    nested_targets: list = []           # (method, nested FunctionDef)
    for m in methods:
        param_types = {a.arg: _ann_class(a.annotation)
                       for a in (m.args.posonlyargs + m.args.args
                                 + m.args.kwonlyargs)}
        local_defs = {st.name: st for st in ast.walk(m)
                      if isinstance(st, ast.FunctionDef) and st is not m}
        for sub in ast.walk(m):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    attr = is_self_attr(t)
                    if attr is None:
                        continue
                    val = sub.value
                    if _is_lock_ctor(val):
                        cls.lock_attrs.add(attr)
                    elif isinstance(val, ast.Call):
                        ctor = call_name(val)
                        if ctor in SAFE_CTORS:
                            cls.safe_attrs.add(attr)
                        elif ctor and ctor.lstrip("_")[:1].isupper():
                            cls.attr_types[attr] = ctor
                    elif (isinstance(val, ast.Name)
                          and param_types.get(val.id)):
                        cls.attr_types[attr] = param_types[val.id]
            elif isinstance(sub, ast.Call):
                for tgt in _thread_target_names(sub):
                    attr = is_self_attr(tgt)
                    if attr is not None:
                        cls.thread_targets.add(attr)
                    elif (isinstance(tgt, ast.Name)
                          and tgt.id in local_defs):
                        nested_targets.append((m, local_defs[tgt.id]))

    # pass 2: per-function events
    for m in methods:
        fi = FuncInfo(qualname=f"{cls.name}.{m.name}", name=m.name,
                      node=m, relpath=module.relpath, cls=cls,
                      is_init=(m.name == "__init__"))
        _scan_func(fi, module)
        cls.funcs[m.name] = fi
    for host, nd in nested_targets:
        qual = f"{cls.name}.{host.name}.{nd.name}"
        fi = FuncInfo(qualname=qual, name=qual, node=nd,
                      relpath=module.relpath, cls=cls)
        _scan_func(fi, module)
        cls.funcs[qual] = fi
        cls.thread_targets.add(qual)
    return cls


def index_module(path: str, relpath: str, source: "str | None" = None
                 ) -> "ModuleInfo | None":
    if source is None:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            return None
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError:
        return None
    mod = ModuleInfo(relpath=relpath, path=path, tree=tree)
    for st in tree.body:
        if isinstance(st, ast.Assign) and _is_lock_ctor(st.value):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    mod.module_locks.add(t.id)
    for st in tree.body:
        if isinstance(st, ast.ClassDef):
            mod.classes[st.name] = _index_class(st, mod)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FuncInfo(qualname=st.name, name=st.name, node=st,
                          relpath=relpath)
            _scan_func(fi, mod)
            mod.functions[st.name] = fi
    return mod


def build_index(root: str, scan: "list[str] | None" = None) -> Index:
    if scan is None:
        scan = [os.path.join(root, "mmlspark_tpu"),
                os.path.join(root, "bench.py")]
    paths = []
    for entry in scan:
        if os.path.isfile(entry):
            paths.append(entry)
            continue
        for base, _dirs, names in os.walk(entry):
            paths.extend(os.path.join(base, n) for n in names
                         if n.endswith(".py"))
    idx = Index(root=root, modules=[])
    for path in sorted(paths):
        mod = index_module(path, os.path.relpath(path, root))
        if mod is None:
            continue
        idx.modules.append(mod)
        for cls in mod.classes.values():
            idx.classes_by_name.setdefault(cls.name, cls)
    return idx


def index_source(source: str, relpath: str = "selftest.py") -> Index:
    """Single-module index for rule selftests."""
    idx = Index(root=".", modules=[])
    mod = index_module(relpath, relpath, source=source)
    if mod is not None:
        idx.modules.append(mod)
        for cls in mod.classes.values():
            idx.classes_by_name.setdefault(cls.name, cls)
    return idx


# -- fixpoints shared by R1/R2/R3 ----------------------------------------- #


def thread_reachable(idx: Index) -> "dict[int, set[str]]":
    """Per-class (keyed by id(ClassInfo)) set of func names reachable
    from a thread entry point, propagated through ``self.m()`` calls and
    one level of ``self.X.m()`` across constructor-typed attributes."""
    reach: dict[int, set[str]] = {}

    def close_over_self_calls(cls: ClassInfo, seed: "set[str]") -> set:
        out = set(seed)
        frontier = list(seed)
        while frontier:
            fname = frontier.pop()
            fi = cls.funcs.get(fname)
            if fi is None:
                continue
            for callee, _held, _ln in fi.self_calls():
                if callee in cls.funcs and callee not in out:
                    out.add(callee)
                    frontier.append(callee)
        return out

    all_classes = [cls for mod in idx.modules
                   for cls in mod.classes.values()]
    for cls in all_classes:
        reach[id(cls)] = close_over_self_calls(cls, cls.thread_targets)

    # one level across classes: a thread-reachable method calling
    # self.X.m() makes C2.m (X: C2) thread-reachable in C2
    for cls in all_classes:
        for fname in list(reach[id(cls)]):
            fi = cls.funcs.get(fname)
            if fi is None:
                continue
            for attr, meth, _held, _ln in fi.attr_calls():
                tname = cls.attr_types.get(attr)
                target = idx.classes_by_name.get(tname) if tname else None
                if target is not None and meth in target.funcs:
                    reach[id(target)] = close_over_self_calls(
                        target, reach[id(target)] | {meth})
    return reach


def caller_context(cls: ClassInfo) -> "tuple[set, dict]":
    """(init_phase, inherited) for one class.

    ``init_phase``: func names that only ever run during construction —
    ``__init__`` plus private helpers reachable ONLY from init-phase
    callers. Their writes predate any concurrency, so R1 skips them.

    ``inherited``: private-helper name -> lockset guaranteed held at
    EVERY (non-init) internal call site — the static analogue of
    Eraser's lockset refinement. A helper like ``_tick`` that is only
    invoked under ``self._lock`` is guarded even though its own body
    shows no ``with``. Public methods and thread entry points inherit
    nothing (they are externally callable)."""
    sites: dict[str, list] = {n: [] for n in cls.funcs}
    for caller, fi in cls.funcs.items():
        for callee, held, _ln in fi.self_calls():
            if callee in sites:
                sites[callee].append((caller, frozenset(held)))

    def private(n: str) -> bool:
        leaf = n.rsplit(".", 1)[-1]
        return leaf.startswith("_") and not (leaf.startswith("__")
                                             and leaf.endswith("__"))

    init_phase: set = {n for n, fi in cls.funcs.items() if fi.is_init}
    changed = True
    while changed:
        changed = False
        for n in cls.funcs:
            if (n in init_phase or not private(n)
                    or n in cls.thread_targets or not sites[n]):
                continue
            if all(c in init_phase for c, _h in sites[n]):
                init_phase.add(n)
                changed = True

    inherited: dict = {}
    eligible = [n for n in cls.funcs
                if private(n) and sites[n] and n not in cls.thread_targets
                and n not in init_phase]
    changed = True
    while changed:
        changed = False
        for n in eligible:
            non_init = [(c, h) for c, h in sites[n]
                        if c not in init_phase]
            if not non_init:
                continue
            new = None
            for c, h in non_init:
                ci = inherited.get(c, frozenset())
                v = h | ci
                new = v if new is None else (new & v)
            if new != inherited.get(n, frozenset()):
                inherited[n] = new
                changed = True
    return init_phase, inherited


def transitive_acquires(cls: ClassInfo) -> "dict[str, set[str]]":
    """func name -> lock ids acquired by the func or any self-callee."""
    direct = {n: {lid for lid, _h, _ln in fi.acquires}
              for n, fi in cls.funcs.items()}
    changed = True
    while changed:
        changed = False
        for n, fi in cls.funcs.items():
            for callee, _h, _ln in fi.self_calls():
                extra = direct.get(callee)
                if extra and not extra <= direct[n]:
                    direct[n] |= extra
                    changed = True
    return direct


def transitive_blocking(cls: ClassInfo, direct_ops) -> "dict[str, set]":
    """func name -> {(op, lineno)} blocking ops in the func or any
    self-callee. `direct_ops(fi)` yields (op, lineno) pairs."""
    table = {n: set(direct_ops(fi)) for n, fi in cls.funcs.items()}
    changed = True
    while changed:
        changed = False
        for n, fi in cls.funcs.items():
            for callee, _h, _ln in fi.self_calls():
                extra = table.get(callee)
                if extra and not extra <= table[n]:
                    table[n] |= extra
                    changed = True
    return table
