"""graftlint engine: rule registry, baseline suppression, CLI.

A rule produces :class:`Finding`s keyed by ``(rule, file, func, match)``
— deliberately NOT by line number, so a baseline entry survives
unrelated edits to the file. The baseline (``baseline.json`` beside
this package) is a list of those keys plus a mandatory one-line ``why``;
policy (enforced by review, verbalized in docs/analysis.md): R1–R3
findings are fixed, never baselined — only R4–R6 and M-rules may carry
entries, each with a justification.

Exit codes: 0 clean; a single failing rule exits with that rule's own
code (R1..R6 -> 11..16, M1..M7 -> 21..27); multiple failing rules -> 1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass, field

ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", ".."))
BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

RULE_EXIT = {f"R{i}": 10 + i for i in range(1, 7)}
RULE_EXIT.update({f"M{i}": 20 + i for i in range(1, 8)})


@dataclass
class Finding:
    rule: str
    file: str
    line: int
    func: str
    match: str
    message: str

    def key(self) -> tuple:
        return (self.rule, self.file, self.func, self.match)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "func": self.func, "match": self.match,
                "message": self.message}


@dataclass
class Rule:
    id: str
    title: str
    run: "callable"          # (Index) -> list[Finding]
    selftest: "callable"     # () -> list[str] problems (empty = pass)
    doc: str = ""


_REGISTRY: "list[Rule]" = []


def register(rule: Rule) -> Rule:
    _REGISTRY.append(rule)
    return rule


def rules() -> "list[Rule]":
    if not _REGISTRY:
        # import for side effect: each module registers its rules
        from . import rules_concurrency  # noqa: F401
        from . import rules_determinism  # noqa: F401
        from . import rules_device  # noqa: F401
        from . import rules_metrics  # noqa: F401
    return list(_REGISTRY)


# -- baseline ------------------------------------------------------------- #


def load_baseline(path: str = BASELINE_PATH) -> "list[dict]":
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        entries = json.load(fh)
    for e in entries:
        missing = {"rule", "file", "func", "match", "why"} - set(e)
        if missing:
            raise SystemExit(
                f"graftlint: baseline entry {e!r} missing {sorted(missing)}")
        if not str(e["why"]).strip():
            raise SystemExit(
                f"graftlint: baseline entry {e!r} has an empty 'why' — "
                "every suppression must carry a justification")
        if e["rule"] in ("R1", "R2", "R3"):
            raise SystemExit(
                f"graftlint: baseline entry {e!r} suppresses {e['rule']} — "
                "concurrency findings are fixed, never baselined")
    return entries


def split_suppressed(findings: "list[Finding]", baseline: "list[dict]"
                     ) -> "tuple[list[Finding], list[Finding], list[dict]]":
    """(unsuppressed, suppressed, stale-baseline-entries)."""
    index = {}
    for e in baseline:
        index[(e["rule"], e["file"], e["func"], e["match"])] = e
    live, quiet, hit = [], [], set()
    for f in findings:
        exact = index.get(f.key())
        wild = index.get((f.rule, f.file, "*", f.match))
        entry = exact or wild
        if entry is not None:
            quiet.append(f)
            hit.add(id(entry))
        else:
            live.append(f)
    stale = [e for e in baseline if id(e) not in hit]
    return live, quiet, stale


# -- run ------------------------------------------------------------------ #


def run_rules(root: str = ROOT, only: "set[str] | None" = None
              ) -> "list[Finding]":
    from .astinfo import build_index
    idx = build_index(root)
    out: list[Finding] = []
    for rule in rules():
        if only and rule.id not in only:
            continue
        out.extend(rule.run(idx))
    return out


def run_selftests(only: "set[str] | None" = None) -> "list[str]":
    problems = []
    for rule in rules():
        if only and rule.id not in only:
            continue
        try:
            problems.extend(f"{rule.id}: {p}" for p in rule.selftest())
        except Exception as exc:  # noqa: BLE001 — a crash IS a failure
            problems.append(f"{rule.id}: selftest crashed: {exc!r}")
    return problems


def _table(findings: "list[Finding]") -> str:
    rows = [(f.rule, f"{f.file}:{f.line}", f.func, f.message)
            for f in findings]
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    return "\n".join(
        f"  {r[0]:<{widths[0]}}  {r[1]:<{widths[1]}}  "
        f"{r[2]:<{widths[2]}}  {r[3]}" for r in rows)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="concurrency & device-hazard static analysis")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--selftest", action="store_true",
                    help="run each rule against its seeded-violation and "
                         "clean-twin fixtures")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--root", default=ROOT)
    ap.add_argument("--list", action="store_true",
                    help="list registered rules and exit")
    args = ap.parse_args(argv)

    only = {r.strip().upper() for r in args.rules.split(",")
            if r.strip()} or None

    if args.list:
        for rule in rules():
            print(f"{rule.id:<3} exit={RULE_EXIT[rule.id]:<3} {rule.title}")
        return 0

    if args.selftest:
        problems = run_selftests(only)
        if problems:
            print(f"graftlint --selftest: {len(problems)} failure(s):")
            for p in problems:
                print(f"  {p}")
            return 1
        n = len([r for r in rules() if not only or r.id in only])
        print(f"graftlint --selftest: {n} rule(s) OK "
              "(seeded violations caught, clean twins pass)")
        return 0

    findings = run_rules(args.root, only)
    baseline = load_baseline(args.baseline)
    if only:
        # staleness is only judged against rules that actually ran
        baseline = [e for e in baseline if e["rule"] in only]
    live, quiet, stale = split_suppressed(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in live],
            "suppressed": [f.as_dict() for f in quiet],
            "stale_baseline": stale,
        }, indent=2, sort_keys=True))
    elif live:
        print(f"graftlint: {len(live)} finding(s) "
              f"({len(quiet)} baselined):")
        print(_table(live))
    else:
        print(f"graftlint: clean ({len(quiet)} baselined finding(s), "
              f"{len(stale)} stale baseline entrie(s))")

    if stale and not live:
        # stale entries rot the baseline: fail so they get pruned
        print("graftlint: stale baseline entries (no longer matched):")
        for e in stale:
            print(f"  {e['rule']} {e['file']} {e['func']} {e['match']}")
        return 2

    if not live:
        return 0
    failing = sorted({f.rule for f in live})
    return RULE_EXIT[failing[0]] if len(failing) == 1 else 1
