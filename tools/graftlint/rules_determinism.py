"""R5 determinism: wall-clock and ambient randomness in replayed paths.

The replay guarantees this repo sells (resume-to-identical-digest,
exactly-once streaming, byte-identical batch re-forms) all assume a
re-run computes the same bytes. ``time.time()``, ``datetime.now()`` and
the ambient ``random`` module are the classic leaks: invisible inputs
that differ across runs. The sanctioned escapes are the injectable
``Clock`` (``resilience/policy.py`` — its SystemClock is the ONLY
module allowed to touch the wall clock) and explicit jax PRNG keys;
``time.monotonic``/``perf_counter`` are allowed everywhere because they
feed telemetry, not data. Remaining wall-clock sites (provenance
timestamps that are metadata, never folded into state) live in the
baseline, each with its justification.
"""

from __future__ import annotations

import ast

from .astinfo import Index, index_source
from .engine import Finding, Rule, register

# modules whose JOB is the wall clock / process randomness
_EXEMPT = ("resilience/policy.py",)

# receiver-name -> forbidden attrs; `time.time` not `t.time`
_FORBIDDEN = {
    "time": {"time", "time_ns"},
    "datetime": {"now", "utcnow", "today"},
    "date": {"today"},
    "random": {"random", "randint", "randrange", "uniform", "choice",
               "choices", "shuffle", "sample", "gauss", "seed",
               "getrandbits"},
}


def _r5_run(idx: Index) -> "list[Finding]":
    out: list[Finding] = []
    for mod, fi in idx.all_funcs():
        if mod.relpath.replace("\\", "/").endswith(_EXEMPT):
            continue
        for node, _held in fi.events:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)):
                continue
            recv, attr = node.func.value.id, node.func.attr
            if attr in _FORBIDDEN.get(recv, ()):
                out.append(Finding(
                    "R5", mod.relpath, node.lineno, fi.qualname,
                    f"call:{recv}.{attr}",
                    f"{recv}.{attr}() is an ambient nondeterministic "
                    f"input in {fi.qualname} — inject a Clock "
                    "(resilience.policy) or a jax PRNG key, or justify "
                    "in the baseline"))
    return out


_R5_BAD = """
import time
def fold(state, row):
    return state + [time.time()]
"""

_R5_CLEAN = """
import time
def fold(state, row, clock):
    t0 = time.perf_counter()
    return state + [clock.monotonic()], time.perf_counter() - t0
"""


def _selftest() -> "list[str]":
    problems = []
    if not _r5_run(index_source(_R5_BAD)):
        problems.append("seeded violation was NOT caught")
    leaked = _r5_run(index_source(_R5_CLEAN))
    if leaked:
        problems.append(f"clean twin produced findings: "
                        f"{[f.message for f in leaked]}")
    return problems


register(Rule(
    id="R5", title="determinism: time.time/datetime.now/ambient random "
    "in paths that must replay byte-identically",
    run=_r5_run, selftest=_selftest))
