"""M1–M7: the metric-hygiene rules, folded in from tools/metric_lint.py.

Same checks, same semantics, one runner: M1 charset, M2 unit suffix,
M3 cross-replica merge policy resolvable, M4 ratio gauges need an
explicit policy, M5 control-plane (gateway/autoscaler) gauges need an
explicit policy, M6 OpenMetrics exemplar syntax + fleet round-trip over
a LIVE exposition, M7 profiler phase vocabulary (static manifest + live
ledger). ``tools/metric_lint.py`` remains as a shim that runs exactly
these rules.
"""

from __future__ import annotations

import os
import re
import sys

from .engine import ROOT, Finding, Rule, register

NAME_RE = re.compile(r"^mmlspark_tpu_[a-z0-9_]+$")
UNIT_SUFFIXES = ("_total", "_seconds", "_bytes", "_ratio", "_depth",
                 "_count", "_rate")
LITERAL_RE = re.compile(
    r"""[fF]?("mmlspark_tpu_[^"\n]*"|'mmlspark_tpu_[^'\n]*')""")
PLACEHOLDER_RE = re.compile(r"\{[^{}]*\}")
_HISTOGRAM_SAMPLE_RE = re.compile(r"_seconds(_bucket|_sum|_count)$")


def _fleet():
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability import fleet
    finally:
        sys.path.pop(0)
    return fleet


def _merge_policy_for(name: str) -> "str | None":
    kind = "counter" if name.endswith("_total") else "gauge"
    return _fleet().merge_policy_for(name, kind)


def _explicit_policy(name: str) -> "str | None":
    return _fleet().GAUGE_MERGE_POLICIES.get(name)


def _iter_literals(text: str):
    for lineno, line in enumerate(text.splitlines(), 1):
        for match in LITERAL_RE.finditer(line):
            yield lineno, PLACEHOLDER_RE.sub("x", match.group(1)[1:-1])


def check_literal(name: str, resolver=None, explicit=None
                  ) -> "tuple[str, str] | None":
    """(rule-id, message) for the FIRST failed check of one metric-name
    literal, or None. `resolver`/`explicit` are injectable for
    selftests (default: the live fleet tables)."""
    resolver = resolver or _merge_policy_for
    explicit = explicit or _explicit_policy
    if not NAME_RE.match(name):
        return ("M1", f"{name!r} violates ^mmlspark_tpu_[a-z0-9_]+$")
    if not name.endswith(UNIT_SUFFIXES):
        return ("M2", f"{name!r} lacks a unit suffix "
                f"({', '.join(UNIT_SUFFIXES)})")
    base = _HISTOGRAM_SAMPLE_RE.sub("_seconds", name)
    if resolver(base) is None:
        return ("M3", f"{name!r} has no cross-replica merge policy (add "
                "it to observability.fleet.GAUGE_MERGE_POLICIES or use "
                "a suffix with a default)")
    if name.endswith("_ratio") and explicit(name) is None:
        return ("M4", f"ratio gauge {name!r} relies on the suffix-"
                "default merge policy — declare max/min intent "
                "explicitly in observability.fleet.GAUGE_MERGE_POLICIES")
    if (name.startswith(("mmlspark_tpu_gateway_",
                         "mmlspark_tpu_autoscaler_"))
            and not name.endswith("_total")
            and not base.endswith("_seconds")
            and explicit(name) is None):
        return ("M5", f"control-plane gauge {name!r} relies on a per-"
                "replica suffix default — gateway/autoscaler series are "
                "driver singletons; add an explicit observability."
                "fleet.GAUGE_MERGE_POLICIES entry")
    return None


def _literal_rule_run(rule_id: str):
    def run(idx) -> "list[Finding]":
        out = []
        for mod in idx.modules:
            try:
                with open(mod.path, encoding="utf-8") as fh:
                    text = fh.read()
            except OSError:
                continue
            for lineno, name in _iter_literals(text):
                hit = check_literal(name)
                if hit and hit[0] == rule_id:
                    out.append(Finding(rule_id, mod.relpath, lineno,
                                       "-", f"name:{name}", hit[1]))
        return out
    return run


def _literal_selftest(rule_id: str, bad_name: str, clean_name: str,
                      resolver=None, explicit=None):
    def selftest() -> "list[str]":
        problems = []
        hit = check_literal(bad_name, resolver, explicit)
        if hit is None or hit[0] != rule_id:
            problems.append(
                f"seeded bad name {bad_name!r} not flagged as {rule_id} "
                f"(got {hit!r})")
        leak = check_literal(clean_name, resolver, explicit)
        if leak is not None and leak[0] == rule_id:
            problems.append(f"clean name {clean_name!r} flagged: {leak}")
        return problems
    return selftest


# -- M6: OpenMetrics exemplar syntax (live exposition) -------------------- #

_EXEMPLAR_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?P<labels>\{[^}]*\})? "
    r"(?P<value>\S+) # \{(?P<ex>[^}]*)\} (?P<ex_value>\S+)$")
_EX_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def lint_exposition(text: str, where: str = "exposition") -> "list[str]":
    """M6 over one rendered exposition: exemplar syntax, the 128-char
    label-set cap, the `# EOF` terminator, and a byte-identical fleet
    parse -> render round trip."""
    fleet = _fleet()
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.metrics import \
            EXEMPLAR_LABEL_SET_MAX
    finally:
        sys.path.pop(0)
    problems = []
    lines = text.splitlines()
    any_exemplar = False
    for lineno, line in enumerate(lines, 1):
        if " # " not in line or line.startswith("#"):
            continue
        any_exemplar = True
        m = _EXEMPLAR_LINE_RE.match(line)
        if m is None:
            problems.append(
                f"{where}:{lineno}: malformed exemplar line {line!r}")
            continue
        if "_bucket" not in m.group("name"):
            problems.append(
                f"{where}:{lineno}: exemplar on non-bucket sample "
                f"{m.group('name')!r}")
        pairs = _EX_PAIR_RE.findall(m.group("ex"))
        total = sum(len(n) + len(v) for n, v in pairs)
        if total > EXEMPLAR_LABEL_SET_MAX:
            problems.append(
                f"{where}:{lineno}: exemplar label set is {total} chars "
                f"(cap {EXEMPLAR_LABEL_SET_MAX})")
        try:
            float(m.group("ex_value"))
        except ValueError:
            problems.append(
                f"{where}:{lineno}: exemplar value "
                f"{m.group('ex_value')!r} is not a number")
    if any_exemplar and (not lines or lines[-1].strip() != "# EOF"):
        problems.append(
            f"{where}: exemplars present but no `# EOF` terminator")
    rendered = fleet.render_families(fleet.parse_prometheus(text))
    if rendered.rstrip("\n") != text.rstrip("\n"):
        problems.append(
            f"{where}: fleet parse -> render round trip is not "
            "byte-identical")
    return problems


def lint_exemplars() -> "list[str]":
    """Render a live exemplar-enabled exposition (and its fleet-merged
    re-render) and run M6 over both."""
    fleet = _fleet()
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.metrics import MetricsRegistry
    finally:
        sys.path.pop(0)
    reg = MetricsRegistry()
    h = reg.histogram("mmlspark_tpu_serving_latency_seconds", "latency",
                      labels=("server",), exemplars=True)
    h.labels(server="srv0").observe(
        0.004, exemplar={"trace_id": "ab" * 16, "route": "resident",
                         "bucket": "8"})
    h.labels(server="srv0").observe(
        2.5, exemplar={"trace_id": "cd" * 16, "route": "host"})
    text = reg.render_prometheus()
    problems = lint_exposition(text, where="registry render")
    merged = fleet.render_families(fleet.parse_prometheus(text))
    problems.extend(lint_exposition(merged, where="fleet re-render"))
    return problems


def _m6_run(idx) -> "list[Finding]":
    return [Finding("M6", "mmlspark_tpu/observability/metrics.py", 0,
                    "-", "exemplar-exposition", p)
            for p in lint_exemplars()]


def _m6_selftest() -> "list[str]":
    problems = []
    seeded = ("mmlspark_tpu_x_seconds_bucket{le=\"1.0\"} 1 "
              "# {trace_id=\"t\"} notanumber")
    if not lint_exposition(seeded, where="seeded"):
        problems.append("seeded malformed exemplar was NOT caught")
    live = lint_exemplars()
    if live:
        problems.append(f"live exposition failed M6: {live}")
    return problems


# -- M7: profiler phase vocabulary ---------------------------------------- #


def lint_profiler_phases(series: "dict | None" = None) -> "list[str]":
    """M7: every ``*_seconds`` profiler histogram declares the ``phase``
    label, and a live ledger only emits phase values from the fixed
    PHASES vocabulary. Pass `series` to check a manifest statically
    (selftest); None runs the full live exercise."""
    sys.path.insert(0, ROOT)
    try:
        from mmlspark_tpu.observability.metrics import MetricsRegistry
        from mmlspark_tpu.observability.profiler import (PHASE_LABEL,
                                                         PHASES,
                                                         PROFILER_SERIES,
                                                         Profiler)
    finally:
        sys.path.pop(0)
    problems = []
    manifest = PROFILER_SERIES if series is None else series
    for name, (kind, labelnames) in sorted(manifest.items()):
        if name.endswith("_seconds") and kind == "histogram" \
                and PHASE_LABEL not in labelnames:
            problems.append(
                f"profiler series {name!r} is a timing histogram without "
                f"a {PHASE_LABEL!r} label — attribution cannot group it "
                "by phase")
    if series is not None:
        return problems
    reg = MetricsRegistry()
    prof = Profiler(registry=reg, enabled=True)
    led = prof.ledger("lint", "seg0")
    for ph in PHASES:
        led.add(ph, 0.001)
    led.note_pad(6, 8)
    led.note_shard("TPU_0", 0.002, rows=6)
    led.done(rtt_s=0.01)
    prof.flush()  # commits drain on a background thread
    try:
        led.add("not_a_phase", 0.001)
    except ValueError:
        pass
    else:
        problems.append(
            "PhaseLedger.add accepted a phase outside PHASES — the "
            "vocabulary is not enforced at the recording site")
    vocab = set(PHASES)
    seen_phases = 0
    for name, fam in reg.snapshot().items():
        for sample in fam.get("samples", []):
            phase = (sample.get("labels") or {}).get(PHASE_LABEL)
            if phase is None:
                continue
            seen_phases += 1
            if phase not in vocab:
                problems.append(
                    f"live profiler emitted phase label {phase!r} on "
                    f"{name!r} — outside the fixed vocabulary "
                    f"{'|'.join(PHASES)}")
    if not seen_phases:
        problems.append(
            "live profiler ledger committed no phase-labeled samples — "
            "the M7 dynamic check is vacuous")
    return problems


def _m7_run(idx) -> "list[Finding]":
    return [Finding("M7", "mmlspark_tpu/observability/profiler.py", 0,
                    "-", "phase-vocabulary", p)
            for p in lint_profiler_phases()]


def _m7_selftest() -> "list[str]":
    problems = []
    seeded = {"mmlspark_tpu_x_seconds": ("histogram", ("segment",))}
    if not lint_profiler_phases(series=seeded):
        problems.append("seeded phase-less histogram was NOT caught")
    live = lint_profiler_phases()
    if live:
        problems.append(f"live profiler exercise failed M7: {live}")
    return problems


register(Rule(
    id="M1", title="metric-name charset (^mmlspark_tpu_[a-z0-9_]+$)",
    run=_literal_rule_run("M1"),
    selftest=_literal_selftest("M1", "mmlspark_tpu_Bad-Name",
                               "mmlspark_tpu_rows_total")))
register(Rule(
    id="M2", title="metric-name unit suffix (Prometheus base units)",
    run=_literal_rule_run("M2"),
    selftest=_literal_selftest("M2", "mmlspark_tpu_rows",
                               "mmlspark_tpu_rows_total")))
register(Rule(
    id="M3", title="cross-replica merge policy resolvable for every "
    "family",
    run=_literal_rule_run("M3"),
    selftest=_literal_selftest(
        "M3", "mmlspark_tpu_rows_count", "mmlspark_tpu_rows_total",
        resolver=lambda name: ("sum" if name.endswith("_total")
                               else None))))
register(Rule(
    id="M4", title="_ratio gauges need an explicit merge policy",
    run=_literal_rule_run("M4"),
    selftest=_literal_selftest(
        "M4", "mmlspark_tpu_zzz_selftest_ratio",
        "mmlspark_tpu_dataplane_pad_waste_ratio")))
register(Rule(
    id="M5", title="gateway/autoscaler gauges need an explicit merge "
    "policy",
    run=_literal_rule_run("M5"),
    selftest=_literal_selftest(
        "M5", "mmlspark_tpu_gateway_zzz_selftest_depth",
        "mmlspark_tpu_gateway_zzz_selftest_total")))
register(Rule(
    id="M6", title="OpenMetrics exemplar syntax + fleet round-trip "
    "(live exposition)",
    run=_m6_run, selftest=_m6_selftest))
register(Rule(
    id="M7", title="profiler phase vocabulary (manifest + live ledger)",
    run=_m7_run, selftest=_m7_selftest))
