"""R1 guarded-by, R2 lock-order, R3 blocking-under-lock.

Lineage: R1 is a static lockset check in the Eraser family (Savage et
al. 1997) restricted to WRITES of ``self`` attributes — reads are
deliberately out of scope (a torn read of a counter is tolerable, a
lost update is not, and write-side discipline is what this codebase's
comments promise). R2/R3 are GoodLock-style (Havelund 2000): a static
lock-acquisition graph whose cycles are potential deadlocks, and a scan
for calls that can block (sleep, socket I/O, fsync, device sync) while
any lock is held. All three propagate one call level through
``self.m()`` (intra-class fixpoint) and ``self.X.m()`` where ``X``'s
class is known from the constructor.

Policy: findings from these three rules are FIXED, never baselined —
the engine rejects R1–R3 baseline entries outright.
"""

from __future__ import annotations

import ast

from .astinfo import (Index, call_name, caller_context, index_source,
                      is_self_attr, thread_reachable,
                      transitive_acquires, transitive_blocking)
from .engine import Finding, Rule, register

# -- R1 ------------------------------------------------------------------- #


def _r1_run(idx: Index) -> "list[Finding]":
    out: list[Finding] = []
    reach = thread_reachable(idx)
    for mod in idx.modules:
        for cls in mod.classes.values():
            if not cls.lock_attrs and not cls.thread_targets \
                    and id(cls) not in reach:
                continue
            creach = reach.get(id(cls), set())
            if not cls.lock_attrs and not creach:
                continue
            init_phase, inherited = caller_context(cls)
            guarded: dict[str, list] = {}
            unguarded: dict[str, list] = {}
            for fname, fi in cls.funcs.items():
                if fname in init_phase:
                    continue            # init-phase: no concurrency yet
                ctx = inherited.get(fname, frozenset())
                for attr, held, lineno, _how in fi.self_writes():
                    if attr in cls.lock_attrs:
                        continue
                    bucket = guarded if (held or ctx) else unguarded
                    bucket.setdefault(attr, []).append((fname, lineno))
            shared_readers = {
                attr
                for fname, fi in cls.funcs.items()
                if fname not in creach and fname not in init_phase
                for attr in (fi.self_reads()
                             | {a for a, *_ in fi.self_writes()})}
            for attr, sites in sorted(unguarded.items()):
                if attr in guarded:
                    gf, gl = guarded[attr][0]
                    for fname, lineno in sites:
                        out.append(Finding(
                            "R1", mod.relpath, lineno,
                            f"{cls.name}.{fname}", f"attr:{attr}",
                            f"self.{attr} written without a lock here "
                            f"but under a lock in {cls.name}.{gf} "
                            f"(line {gl}) — lost-update race"))
                    continue
                for fname, lineno in sites:
                    if fname in creach and attr in shared_readers:
                        out.append(Finding(
                            "R1", mod.relpath, lineno,
                            f"{cls.name}.{fname}", f"attr:{attr}",
                            f"self.{attr} written from thread-reachable "
                            f"{cls.name}.{fname} without any lock, and "
                            "accessed from non-thread methods — data "
                            "race"))
    return out


_R1_BAD = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def a(self):
        with self._lock:
            self.n += 1
    def b(self):
        self.n = 5
"""

_R1_BAD_THREAD = """
import threading
class C:
    def __init__(self):
        self._result = None
        self._thread = threading.Thread(target=self._run)
    def _run(self):
        self._result = 42
    def result(self):
        return self._result
"""

_R1_CLEAN = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def a(self):
        with self._lock:
            self.n += 1
    def b(self):
        with self._lock:
            self.n = 5
"""


# -- R2 ------------------------------------------------------------------- #


def _lock_edges(idx: Index) -> "dict[tuple, tuple]":
    """(src, dst) -> (file, line, func) witness for every ordered pair
    of lock acquisitions the source can perform."""
    edges: dict[tuple, tuple] = {}

    def add(src: str, dst: str, where: tuple) -> None:
        if src != dst:
            edges.setdefault((src, dst), where)

    for mod, fi in idx.all_funcs():
        for lid, held, lineno in fi.acquires:
            for h in held:
                add(h, lid, (mod.relpath, lineno, fi.qualname))

    for mod in idx.modules:
        for cls in mod.classes.values():
            trans = transitive_acquires(cls)
            for fname, fi in cls.funcs.items():
                for callee, held, lineno in fi.self_calls():
                    if not held:
                        continue
                    for lid in trans.get(callee, ()):
                        for h in held:
                            add(h, lid,
                                (mod.relpath, lineno, fi.qualname))
                for attr, meth, held, lineno in fi.attr_calls():
                    if not held:
                        continue
                    tname = cls.attr_types.get(attr)
                    target = (idx.classes_by_name.get(tname)
                              if tname else None)
                    if target is None:
                        continue
                    ttrans = transitive_acquires(target)
                    for lid in ttrans.get(meth, ()):
                        for h in held:
                            add(h, lid,
                                (mod.relpath, lineno, fi.qualname))
    return edges


def _r2_run(idx: Index) -> "list[Finding]":
    edges = _lock_edges(idx)
    graph: dict[str, set] = {}
    for (src, dst) in edges:
        graph.setdefault(src, set()).add(dst)

    # iterative Tarjan SCC
    index_of: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index_of:
                    index_of[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index_of[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index_of[node]:
                comp = []
                while True:
                    top = stack.pop()
                    on_stack.discard(top)
                    comp.append(top)
                    if top == node:
                        break
                sccs.append(comp)

    for node in sorted(graph):
        if node not in index_of:
            strongconnect(node)

    out = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        comp = sorted(comp)
        witnesses = sorted(
            f"{src}->{dst} at {w[0]}:{w[1]} ({w[2]})"
            for (src, dst), w in edges.items()
            if src in comp and dst in comp)
        rel, line = edges[next(
            (s, d) for (s, d) in edges if s in comp and d in comp)][:2]
        out.append(Finding(
            "R2", rel, line, "-", "cycle:" + "|".join(comp),
            "lock-order cycle (potential deadlock) among "
            f"{{{', '.join(comp)}}}; witnesses: "
            + "; ".join(witnesses)))
    return out


_R2_BAD = """
import threading
class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def x(self):
        with self._a:
            with self._b:
                pass
    def y(self):
        with self._b:
            with self._a:
                pass
"""

_R2_CLEAN = """
import threading
class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def x(self):
        with self._a:
            with self._b:
                pass
    def y(self):
        with self._a:
            with self._b:
                pass
"""


# -- R3 ------------------------------------------------------------------- #

# blocking by attribute name (os.fsync, sock.recv, time.sleep,
# clock.sleep, arr.block_until_ready, conn.getresponse, ...)
_BLOCKING_ATTRS = {"sleep", "fsync", "block_until_ready", "recv",
                   "recv_into", "sendall", "sendto", "accept", "connect",
                   "getresponse", "urlopen", "create_connection",
                   "serve_forever"}
_BLOCKING_NAMES = {"urlopen", "http_send", "create_connection"}


def _blocking_ops(fi) -> "list[tuple[str, int]]":
    out = []
    for node, _held in fi.events:
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if isinstance(node.func, ast.Attribute):
            if name in _BLOCKING_ATTRS:
                out.append((name, node.lineno))
        elif name in _BLOCKING_NAMES:
            out.append((name, node.lineno))
    return out


def _r3_run(idx: Index) -> "list[Finding]":
    out: list[Finding] = []
    for mod, fi in idx.all_funcs():
        for node, held in fi.events:
            if not held or not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            hit = (name in _BLOCKING_ATTRS
                   if isinstance(node.func, ast.Attribute)
                   else name in _BLOCKING_NAMES)
            if hit:
                out.append(Finding(
                    "R3", mod.relpath, node.lineno, fi.qualname,
                    f"op:{name}",
                    f"blocking call {name}() while holding "
                    f"{{{', '.join(held)}}}"))
    # one propagated level: calling a method that blocks, under a lock
    for mod in idx.modules:
        for cls in mod.classes.values():
            trans = transitive_blocking(cls, _blocking_ops)
            for fname, fi in cls.funcs.items():
                for callee, held, lineno in fi.self_calls():
                    ops = trans.get(callee)
                    if held and ops:
                        opnames = sorted({o for o, _l in ops})
                        out.append(Finding(
                            "R3", mod.relpath, lineno,
                            f"{cls.name}.{fname}",
                            f"call:{callee}",
                            f"calls self.{callee}() which performs "
                            f"{'/'.join(opnames)} while holding "
                            f"{{{', '.join(held)}}}"))
    return out


_R3_BAD = """
import threading, time
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def a(self):
        with self._lock:
            time.sleep(1)
"""

_R3_BAD_PROPAGATED = """
import os, threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = open("x", "a")
    def _append(self):
        os.fsync(self._fh.fileno())
    def record(self):
        with self._lock:
            self._append()
"""

_R3_CLEAN = """
import threading, time
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def a(self):
        with self._lock:
            n = 1
        time.sleep(n)
"""


# -- selftest plumbing ---------------------------------------------------- #


def _fixture_selftest(run, bad_sources: "list[str]", clean: str,
                      relpath: str = "selftest.py"):
    def selftest() -> "list[str]":
        problems = []
        for i, src in enumerate(bad_sources):
            if not run(index_source(src, relpath)):
                problems.append(
                    f"seeded violation #{i} was NOT caught")
        leaked = run(index_source(clean, relpath))
        if leaked:
            problems.append(
                f"clean twin produced findings: "
                f"{[f.message for f in leaked]}")
        return problems
    return selftest


register(Rule(
    id="R1", title="guarded-by: self-attribute writes with inconsistent "
    "or missing lock protection",
    run=_r1_run,
    selftest=_fixture_selftest(_r1_run, [_R1_BAD, _R1_BAD_THREAD],
                               _R1_CLEAN)))

register(Rule(
    id="R2", title="lock-order: cycles in the static lock-acquisition "
    "graph (potential deadlocks)",
    run=_r2_run,
    selftest=_fixture_selftest(_r2_run, [_R2_BAD], _R2_CLEAN)))

register(Rule(
    id="R3", title="blocking-under-lock: sleep/socket/fsync/device-sync "
    "while holding a lock",
    run=_r3_run,
    selftest=_fixture_selftest(_r3_run, [_R3_BAD, _R3_BAD_PROPAGATED],
                               _R3_CLEAN)))
