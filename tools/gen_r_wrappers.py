#!/usr/bin/env python
"""Generate the R language surface (r/mmlsparktpu/) from the stage registry.

Reference: `SparklyRWrapper` (src/codegen/src/main/scala/
SparklyRWrapper.scala:21-196) reflects over every pipeline stage and emits
one `ml_<stage>` R function (roxygen docs from Param docs, R-typed
defaults, `as.integer`/`as.logical`/`as.double` conversions, fit+transform
semantics for estimators) plus the package NAMESPACE/DESCRIPTION
(WrapperGenerator.scala:244).

TPU redesign: R calls Python directly through `reticulate` — no JVM, no
Spark connection object. The generated package has ONE bridge helper
(`.tpu_apply_stage` in R/package.R) and one thin generated function per
registered stage; `tpu_table`/`tpu_collect` convert data.frame <-> Table
at the boundary. The same registry the fuzzing suite enforces coverage
over drives generation, so the R surface can never silently trail the
Python one (tests/test_r_wrappers.py keeps the committed output fresh,
exactly like docs/api.md).

Usage: python tools/gen_r_wrappers.py          # rewrites r/mmlsparktpu/
       python tools/gen_r_wrappers.py --check  # exit 1 if stale
"""

from __future__ import annotations

import importlib
import math
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SUBPACKAGES = ("core", "gbdt", "nn", "image", "ops", "text", "automl",
               "recommendation", "io_http", "plot", "parallel", "streaming",
               "resilience", "observability", "utils")

R_DIR = os.path.join(os.path.dirname(__file__), "..", "r", "mmlsparktpu")

# R reserved words can never be argument names; none of the registry's
# params collide today and the generator refuses if one ever does
R_RESERVED = {"if", "else", "repeat", "while", "function", "for", "next",
              "break", "TRUE", "FALSE", "NULL", "Inf", "NaN", "NA"}


def snake(name: str) -> str:
    s = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name)
    s = re.sub(r"(?<=[A-Z])(?=[A-Z][a-z])", "_", s)
    return s.lower()


def r_string(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def r_default(p) -> str | None:
    """R literal for a Param default; None = required (no default)."""
    if p.required:
        return None
    d = p.default
    if d is None:
        return "NULL"
    if isinstance(d, bool):
        return "TRUE" if d else "FALSE"
    if isinstance(d, int):
        return f"{d}L"
    if isinstance(d, float):
        # repr() of a non-finite float is "inf"/"nan" — not valid R. R
        # spells them Inf/-Inf/NaN (all parse as doubles).
        if math.isinf(d):
            return "Inf" if d > 0 else "-Inf"
        if math.isnan(d):
            return "NaN"
        return repr(d)
    if isinstance(d, str):
        return r_string(d)
    if isinstance(d, (list, tuple)) and not d:
        return "NULL"  # empty collection: omit -> python default applies
    return "NULL"      # complex default: reference emits NULL the same way


def r_conversion(p, name: str) -> str:
    """The getParamConversion analogue (SparklyRWrapper.scala:91-100).
    A tuple ptype is a UNION, not a collection: (int, float) wants a
    scalar (as.list would feed Param.validate a rejected list); only
    unions admitting list/tuple/dict convert through as.list."""
    pt = p.ptype
    if isinstance(pt, tuple):
        if any(t in (list, tuple, dict) for t in pt):
            return f"as.list({name})"
        if float in pt:
            return f"as.double({name})"
        if int in pt:
            return f"as.integer({name})"
        if str in pt:
            return f"as.character({name})"
        return name
    if pt is bool:
        return f"as.logical({name})"
    if pt is int:
        return f"as.integer({name})"
    if pt is float:
        return f"as.double({name})"
    if pt is str:
        return f"as.character({name})"
    if pt in (list, dict):
        return f"as.list({name})"
    return name


def _role(cls) -> str:
    from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer

    if issubclass(cls, Model):
        return "Model"
    if issubclass(cls, Estimator):
        return "Estimator"
    if issubclass(cls, Transformer):
        return "Transformer"
    return "Stage"


def _summary(cls) -> str:
    import inspect

    doc = cls.__dict__.get("__doc__") or ""
    doc = inspect.cleandoc(doc)
    return doc.split("\n\n", 1)[0].replace("\n", " ").strip()


def stage_function(qual: str, cls) -> tuple[str, str, str]:
    """-> (exported name, file name, R source) for one registered stage."""
    params = getattr(cls, "_params", {})
    fn = f"ml_{snake(cls.__name__)}"
    role = _role(cls)

    sig, body, docs = [], [], []
    for name, p in params.items():
        if name in R_RESERVED:
            raise ValueError(f"{qual}.{name} collides with an R keyword")
        default = r_default(p)
        sig.append(name if default is None else f"{name} = {default}")
        body.append(
            f"  if (!is.null({name})) "
            f"params${name} <- {r_conversion(p, name)}")
        doc = (p.doc or "").replace("\n", " ")
        docs.append(f"#' @param {name} {doc}")

    is_est = role == "Estimator"
    extra_sig = ", only.model = FALSE" if is_est else ""
    extra_doc = (["#' @param only.model return the fitted model without "
                  "transforming x (the reference's unfit.model)"]
                 if is_est else [])
    summary = _summary(cls) or cls.__name__
    lines = [
        f"#' {cls.__name__} ({role})",
        "#'",
        f"#' {summary}",
        "#'",
        "#' @param x a data.frame or tpu_table",
        *docs,
        *extra_doc,
        "#' @export",
        f"{fn} <- function(x{''.join(', ' + s for s in sig)}{extra_sig})",
        "{",
        "  params <- list()",
        *body,
        f"  .tpu_apply_stage({r_string(qual)}, params, x, "
        f"is_estimator = {'TRUE' if is_est else 'FALSE'}"
        f"{', only.model = only.model' if is_est else ''})",
        "}",
        "",
    ]
    return fn, f"{fn[3:]}.R", "\n".join(lines)


PACKAGE_R = '''\
# Bridge runtime for the generated wrappers (the sparklyr-connection
# analogue, SparklyRWrapper.scala:30-52 — here the "connection" is an
# embedded Python interpreter via reticulate).

.tpu_env <- new.env(parent = emptyenv())

.tpu <- function() {
  if (is.null(.tpu_env$pkg)) {
    .tpu_env$pkg <- reticulate::import("mmlspark_tpu")
    for (sub in c({subpackages})) {
      reticulate::import(paste0("mmlspark_tpu.", sub))
    }
  }
  .tpu_env$pkg
}

#' Convert a data.frame (or named list of columns) to a Table
#' @param df a data.frame or named list
#' @export
tpu_table <- function(df) {
  .tpu()
  schema <- reticulate::import("mmlspark_tpu.core.schema")
  # length-1 R vectors would convert to Python SCALARS and break Table's
  # column-length check on 1-row inputs; box ONLY those — longer columns
  # keep reticulate's vectorized double-vector -> array fast path
  cols <- lapply(as.list(df), function(col) {
    if (length(col) == 1L) as.list(col) else col
  })
  schema$Table(reticulate::r_to_py(cols))
}

#' Collect a Table back into a data.frame
#' @param tbl a Table
#' @export
tpu_collect <- function(tbl) {
  cols <- list()
  for (name in tbl$columns) {
    # tbl[name] auto-converts (the module is imported with convert=TRUE);
    # py_to_r here would error on the already-converted R object
    cols[[name]] <- tbl[name]
  }
  as.data.frame(cols, stringsAsFactors = FALSE)
}

.tpu_resolve_class <- function(qualified) {
  parts <- strsplit(qualified, ".", fixed = TRUE)[[1]]
  module <- paste(parts[-length(parts)], collapse = ".")
  cls_name <- parts[length(parts)]
  reticulate::import(module)[[cls_name]]
}

.tpu_apply_stage <- function(qualified, params, x,
                             is_estimator = FALSE, only.model = FALSE) {
  .tpu()
  tbl <- if (inherits(x, "python.builtin.object")) x else tpu_table(x)
  cls <- .tpu_resolve_class(qualified)
  stage <- do.call(cls, params)
  if (is_estimator) {
    model <- stage$fit(tbl)
    if (isTRUE(only.model)) {
      return(model)
    }
    return(model$transform(tbl))
  }
  stage$transform(tbl)
}
'''


def generate() -> dict[str, str]:
    """-> {relative path under r/mmlsparktpu: content}."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    for sub in SUBPACKAGES:
        importlib.import_module(f"mmlspark_tpu.{sub}")
    from mmlspark_tpu import __version__
    from mmlspark_tpu.core.serialize import own_stages

    # single source of truth for the eager-import list (plain replace, not
    # str.format — the R code is full of literal braces)
    subs = ", ".join(f'"{s}"' for s in SUBPACKAGES)
    files: dict[str, str] = {
        "R/package.R": PACKAGE_R.replace("{subpackages}", subs)}
    exports = ["export(tpu_table)", "export(tpu_collect)"]
    seen_fns: dict[str, str] = {}
    # own_stages(), not registry(): generation must not depend on what a
    # host process registered (the fuzzing suite's test stages pollute
    # the process-global registry)
    for qual, cls in sorted(own_stages().items()):
        fn, fname, src = stage_function(qual, cls)
        if fn in seen_fns:
            # bare-name collisions would silently overwrite a wrapper file
            # and dispatch half the calls to the wrong class
            raise ValueError(
                f"R wrapper name collision: {qual} and {seen_fns[fn]} "
                f"both generate {fn}")
        seen_fns[fn] = qual
        files[f"R/{fname}"] = src
        exports.append(f"export({fn})")
    files["NAMESPACE"] = "\n".join(sorted(exports)) + "\n"
    files["DESCRIPTION"] = "\n".join([
        "Package: mmlsparktpu",
        "Type: Package",
        "Title: R bindings for the mmlspark_tpu framework",
        f"Version: {__version__}",
        "Description: Auto-generated R surface (one ml_* function per",
        "    registered pipeline stage) bridging to the TPU-native Python",
        "    framework via reticulate. Regenerate with",
        "    tools/gen_r_wrappers.py; do not edit by hand.",
        "Imports: reticulate",
        "License: MIT",
        "Encoding: UTF-8",
    ]) + "\n"
    return files


def main() -> None:
    files = generate()
    base = os.path.normpath(R_DIR)
    if "--check" in sys.argv:
        stale = []
        for rel, content in files.items():
            path = os.path.join(base, rel)
            try:
                with open(path) as fh:
                    if fh.read() != content:
                        stale.append(rel)
            except FileNotFoundError:
                stale.append(rel)
        on_disk = set()
        for root, _dirs, names in os.walk(base):
            for n in names:
                on_disk.add(os.path.relpath(os.path.join(root, n), base))
        orphans = on_disk - set(files)
        if stale or orphans:
            print(f"r/mmlsparktpu is stale (changed: {sorted(stale)[:5]}, "
                  f"orphaned: {sorted(orphans)[:5]}) — "
                  "run python tools/gen_r_wrappers.py")
            raise SystemExit(1)
        print(f"r/mmlsparktpu up to date ({len(files)} files)")
        return
    import shutil

    if os.path.isdir(base):
        shutil.rmtree(base)
    for rel, content in files.items():
        path = os.path.join(base, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write(content)
    print(f"wrote {len(files)} files under {base}")


if __name__ == "__main__":
    main()
