#!/usr/bin/env bash
# One-shot TPU measurement session: run everything that needs the real chip
# while a tunnel window is open. Outputs land in tpu_session_out/.
#
#   tools/tpu_session.sh           # probe, then sweep + bench
set -uo pipefail
cd "$(dirname "$0")/.."
OUT=tpu_session_out
mkdir -p "$OUT"

echo "== probe =="
if ! timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.platform, d.device_kind)" \
    > "$OUT/probe.txt" 2>&1; then
  echo "probe failed/hung — tunnel down"; cat "$OUT/probe.txt" | tail -2; exit 1
fi
cat "$OUT/probe.txt"

echo "== kernel sweep =="
timeout 1200 python -u tools/sweep_hist.py > "$OUT/sweep.txt" 2>&1
tail -12 "$OUT/sweep.txt"

echo "== bench =="
timeout 2400 python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"
tail -1 "$OUT/bench.json"

echo "== done — outputs in $OUT/ =="
