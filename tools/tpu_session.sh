#!/usr/bin/env bash
# One-shot TPU measurement session: run everything that needs the real chip
# while a tunnel window is open. Outputs land in tpu_session_out/.
#
# ORDER MATTERS: observed windows last ~30 min (2026-07-30 ~22:45 and
# 2026-07-31 03:46 sessions both lost the tunnel ~30 min in). The Pallas
# AOT-compile gate runs first — per-kernel Mosaic verdicts before any
# timed run (VERDICT r4 #2), normally a few min but capped at 900 s; the
# bench — the artifact the round is judged on — follows immediately, and
# sweeps/diagnostics use whatever window remains.
#
#   tools/tpu_session.sh           # probe, then bench + sweeps
set -uo pipefail
cd "$(dirname "$0")/.."
# scripts under tools/ put tools/ at sys.path[0]; the package lives at root
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
# fresh $OUT per session: stale files from an earlier window must never be
# archived under (and misattributed to) this session's timestamp. A session
# killed mid-run never reaches its own archive step, so rescue any leftover
# capture FIRST — chip windows are too rare to ever delete one's data.
OUT=tpu_session_out
if [ -d "$OUT" ] && [ -n "$(ls -A "$OUT" 2>/dev/null)" ]; then
  RESCUE="sweeps/rescued_$(date -u +%Y%m%dT%H%M%SZ)"
  mkdir -p "$RESCUE"
  cp -r "$OUT"/. "$RESCUE/" 2>/dev/null || true
  for f in "$RESCUE"/*.log; do
    [ -e "$f" ] && mv "$f" "${f%.log}_log.txt"
  done
  echo "rescued previous session leftovers to $RESCUE"
fi
rm -rf "$OUT"
mkdir -p "$OUT"

echo "== probe =="
if ! timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.platform, d.device_kind)" \
    > "$OUT/probe.txt" 2>&1; then
  echo "probe failed/hung — tunnel down"; cat "$OUT/probe.txt" | tail -2; exit 1
fi
cat "$OUT/probe.txt"

rc=0

echo "== Pallas AOT-compile gate (every shipped kernel, real Mosaic, before any timed run) =="
# interpret parity is not compile evidence (the fused kernel's r4 lesson);
# a FAIL here is a recorded fact the bench's fallbacks then ride around —
# non-fatal so a kernel bug cannot burn the window
if timeout 900 python -u tools/aot_gate.py > "$OUT/aot_gate.txt" 2>&1; then
  grep -A99 "AOT GATE SUMMARY" "$OUT/aot_gate.txt" || tail -10 "$OUT/aot_gate.txt"
else
  echo "AOT GATE TIMED OUT/CRASHED — tail of $OUT/aot_gate.txt:"
  tail -5 "$OUT/aot_gate.txt"
fi

echo "== bench (the judged artifact; probes capped: the watcher just proved the tunnel up) =="
# worst case inside the orchestrator: device core attempt (1800s) + CPU
# core retry (1800s) + transformer (900s) + trainer (900s) + gbdt_large
# (1200s) children — the outer guard must cover it (solo children force
# CPU and finish fast when the core already fell back)
if timeout 6900 env MMLSPARK_TPU_BENCH_PROBE_ATTEMPTS=2 \
    python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"; then
  tail -1 "$OUT/bench.json"
else
  echo "BENCH FAILED (rc=$?) — tail of $OUT/bench.err:"; tail -5 "$OUT/bench.err"
  rc=1
fi

echo "== measured-latency gate (tight: the ~1 ms serving claim) =="
# The CI suite keeps loose noise-guards (test_serving.py); the TIGHT gate
# lives here, where the numbers are measured on the real chip session:
# p50 <= 1.5 ms, p99 <= 5 ms, or this scripted check fails.
if ! python - "$OUT/bench.json" <<'PYEOF'
import json, sys
line = open(sys.argv[1]).read().strip().splitlines()[-1]
e = json.loads(line)["extra"]
if e.get("platform") in (None, "cpu"):
    print("latency gate skipped: bench ran on CPU fallback")
    sys.exit(0)
p50, p99 = e.get("serving_p50_ms"), e.get("serving_p99_ms")
assert p50 is not None and p99 is not None, "no serving latency in bench"
assert p50 <= 1.5, f"serving p50 {p50} ms exceeds 1.5 ms gate"
assert p99 <= 5.0, f"serving p99 {p99} ms exceeds 5 ms gate"
# the full client round trip (catches transport stalls the server-side
# window can't see — the Nagle/delayed-ACK class)
c50, c99 = e.get("serving_client_rtt_p50_ms"), e.get("serving_client_rtt_p99_ms")
assert c50 is None or c50 <= 3.0, f"client RTT p50 {c50} ms exceeds 3 ms gate"
assert c99 is None or c99 <= 10.0, f"client RTT p99 {c99} ms exceeds 10 ms gate"
print(f"latency gate OK: p50={p50} p99={p99} rtt_p50={c50} rtt_p99={c99} ms")
PYEOF
then
  echo "LATENCY GATE FAILED"
  rc=1
fi

echo "== kernel sweep (µs/build variants + the FULL-FIT A/B decision table) =="
if timeout 1800 python -u tools/sweep_hist.py > "$OUT/sweep.txt" 2>&1; then
  tail -12 "$OUT/sweep.txt"
else
  echo "SWEEP FAILED (rc=$?) — tail of $OUT/sweep.txt:"; tail -5 "$OUT/sweep.txt"
  rc=1
fi

echo "== batch sweep (runner fwd + resnet50 trainer step) =="
if timeout 1200 python -u tools/sweep_batch.py --out "$OUT/batch_sweep.csv" \
    > "$OUT/batch_sweep.txt" 2>&1; then
  tail -12 "$OUT/batch_sweep.txt"
else
  echo "BATCH SWEEP FAILED (rc=$?) — tail of $OUT/batch_sweep.txt:"
  tail -5 "$OUT/batch_sweep.txt"
  rc=1
fi

echo "== dispatch diagnostic (tunnel RTT vs fused scan) =="
if timeout 600 python -u tools/diag_tunnel.py > "$OUT/diag.txt" 2>&1; then
  tail -6 "$OUT/diag.txt"
else
  echo "DIAG FAILED (rc=$?) — tail of $OUT/diag.txt:"; tail -3 "$OUT/diag.txt"
  rc=1
fi

echo "== xprof trace of a GBDT fit (for roofline analysis next round) =="
if timeout 600 env MMLSPARK_TPU_TRACE_DIR="$OUT/xprof" \
    MMLSPARK_TPU_BENCH_PROBE_ATTEMPTS=1 python - > "$OUT/trace.txt" 2>&1 <<'PYEOF'
import numpy as np
from mmlspark_tpu.gbdt.booster import Booster, TrainOptions
from mmlspark_tpu.utils.profiling import device_trace
import os
rng = np.random.default_rng(7)
x = rng.normal(size=(1 << 18, 28)); y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(float)
opts = TrainOptions(objective="binary", num_iterations=20, num_leaves=63)
Booster.train(x, y, opts)                 # compile warm-up outside the trace
with device_trace(os.environ["MMLSPARK_TPU_TRACE_DIR"]):
    Booster.train(x, y, opts)
print("trace captured")
PYEOF
then
  tail -1 "$OUT/trace.txt"
else
  echo "TRACE FAILED (non-fatal):"; tail -3 "$OUT/trace.txt"
fi

# archive this window's capture so a re-fired session (watcher re-arms on
# rc!=0) can never clobber it; .log -> _log.txt because *.log is gitignored
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
ARCHIVE="sweeps/session_$STAMP"
mkdir -p "$ARCHIVE"
cp -r "$OUT"/. "$ARCHIVE/" 2>/dev/null || true
for f in "$ARCHIVE"/*.log; do
  [ -e "$f" ] && mv "$f" "${f%.log}_log.txt"
done

if [ "$rc" -eq 0 ]; then
  echo "== done — outputs in $OUT/ (archived sweeps/session_$STAMP) =="
else
  echo "== FINISHED WITH FAILURES — outputs in $OUT/ (archived sweeps/session_$STAMP) =="
fi
exit "$rc"
