"""Measure the tunneled device's per-dispatch cost, separated from compute.

The round-4 sweep saw ResNet-50 224px training at ~0.4% MFU under a
one-dispatch-per-step host loop while a fused forward hit near-peak — the
suspected culprit is per-dispatch client latency on the remote (axon
tunnel) device, which a lax.scan-fused dispatch amortizes away. This
prints the numbers that settle it:

  rtt_tiny_ms        — N dependent dispatches of a trivial jitted op
                       (x @ w, 128x128): pure dispatch round-trip.
  rtt_tiny_donated   — same with buffer donation (donation can force the
                       client to synchronize on remote runtimes).
  scan_tiny_ms       — the same N trivial steps fused in one lax.scan
                       dispatch: the floor dispatch cost once amortized.
  fwd224_ms          — one ResNet-50 224px bf16 forward, bs=32: is the
                       *forward* compute itself sane on this chip?

Usage: python tools/diag_tunnel.py  (run on the real chip)
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 16


def timed(fn, *args, reps=3):
    out = fn(*args)
    import jax

    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    from bench import pin_cpu_if_requested

    pin_cpu_if_requested()

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})")

    w = jnp.eye(128, dtype=jnp.float32) * 0.999
    x0 = jnp.ones((128, 128), jnp.float32)

    step = jax.jit(lambda x: x @ w)

    def loop(x):
        for _ in range(N_STEPS):
            x = step(x)
        return x

    jax.block_until_ready(loop(x0))  # warm
    t = timed(loop, x0)
    print(f"rtt_tiny_ms          {t / N_STEPS * 1e3:8.3f}   "
          f"({N_STEPS} dependent dispatches, trivial op)", flush=True)

    step_don = jax.jit(lambda x: x @ w, donate_argnums=(0,))

    def loop_don(_):
        x = jnp.ones((128, 128), jnp.float32)
        for _ in range(N_STEPS):
            x = step_don(x)
        return x

    jax.block_until_ready(loop_don(None))
    t = timed(loop_don, None)
    print(f"rtt_tiny_donated_ms  {t / N_STEPS * 1e3:8.3f}   "
          f"(same, with donation)", flush=True)

    scan = jax.jit(
        lambda x: jax.lax.scan(lambda c, _: (c @ w, None), x, None,
                               length=N_STEPS)[0])
    jax.block_until_ready(scan(x0))
    t = timed(scan, x0)
    print(f"scan_tiny_ms         {t / N_STEPS * 1e3:8.3f}   "
          f"(same steps fused in one scan dispatch)", flush=True)

    from mmlspark_tpu.nn.models import make_model

    on_cpu = dev.platform == "cpu"
    arch, side, gflop_img = (("resnet20_cifar", 32, 0.041) if on_cpu
                             else ("resnet50", 224, 4.1))
    module = make_model(arch, num_outputs=10, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.integers(0, 256, size=(32, side, side, 3),
                                  dtype=np.uint8))
    variables = module.init(jax.random.PRNGKey(0), xb[:1].astype(jnp.float32))
    fwd = jax.jit(lambda v, x: module.apply(v, x.astype(jnp.float32),
                                            train=False))
    t = timed(fwd, variables, xb)
    gflop = gflop_img * 32
    print(f"fwd_{side}px_ms      {t * 1e3:8.3f}   "
          f"({arch} bs=32 fwd ≈ {gflop / t / 1e3:.1f} TFLOP/s)", flush=True)


if __name__ == "__main__":
    main()
