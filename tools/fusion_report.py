#!/usr/bin/env python
"""Print the pipeline-fusion segment plan for exemplar pipelines.

`fuse()` (core/fusion.py) partitions a PipelineModel into maximal
device-capable runs; each run compiles into ONE jitted composition.
Whether a given stage fuses is a static property of its configuration
(its `device_kernel()` declaration), so the plan can drift silently when
a stage gains a parameter its kernel doesn't support. This report makes
the plan a CI-visible artifact: it builds one exemplar pipeline per
wired stage family, prints `FusionPlan.describe()` for each, and FAILS
if a pipeline that is expected to fuse fully no longer does.

Usage: python tools/fusion_report.py    # exit 1 if an expectation breaks
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build_pipelines():
    """-> list of (title, PipelineModel, expected_fusion_ratio)."""
    from mmlspark_tpu.core.pipeline import pipeline_model
    from mmlspark_tpu.core.schema import Table
    from mmlspark_tpu.gbdt.estimators import GBDTRegressor
    from mmlspark_tpu.image.transformer import ImageTransformer
    from mmlspark_tpu.nn.models import ModelBundle
    from mmlspark_tpu.nn.runner import DeepModelTransformer
    from mmlspark_tpu.ops.conversion import DataConversion
    from mmlspark_tpu.ops.ensemble import EnsembleByKey
    from mmlspark_tpu.ops.featurize import AssembleFeatures
    from mmlspark_tpu.ops.missing import CleanMissingData

    rng = np.random.default_rng(0)
    tab = Table({c: rng.normal(size=32) for c in "abcd"})
    asm = AssembleFeatures(columns_to_featurize=list("abcd")).fit(tab)
    clean = CleanMissingData(
        input_cols=["a"], output_cols=["a"], cleaning_mode="Mean",
    ).fit(Table({"a": tab["a"].astype(np.float32)}))
    mlp = DeepModelTransformer(input_col="features").set_model(
        ModelBundle.init("mlp", (4,), seed=0, num_outputs=2))
    conv = DataConversion(cols=["output"], convert_to="float")
    image = (ImageTransformer(input_col="image", output_col="image")
             .resize(8, 8).gray(keep_channels=True))
    gbdt = GBDTRegressor(
        features_col="features", label_col="label", num_iterations=4,
        num_leaves=7,
    ).fit(Table({"features": rng.normal(size=(64, 3)),
                 "label": rng.normal(size=64)}))
    ens = EnsembleByKey(keys=["k"], cols=["output"])

    return [
        ("tabular scoring (assemble -> clean -> mlp -> convert)",
         pipeline_model(clean, asm, mlp, conv), 1.0),
        ("image scoring (op chain -> mlp)",
         pipeline_model(image, mlp), 1.0),
        ("gbdt regression", pipeline_model(asm, gbdt), 1.0),
        ("host sandwich (ensemble groupby splits the run)",
         pipeline_model(asm, mlp, ens, conv), 0.75),
    ]


def main() -> int:
    import jax

    from mmlspark_tpu.core.fusion import plan_fusion

    # with >1 device (e.g. XLA_FLAGS=--xla_force_host_platform_device_count)
    # describe the plan against the full-device mesh; single-device CI keeps
    # mesh=1 and still prints each kernel's sharding contract
    mesh = None
    if len(jax.devices()) > 1:
        from mmlspark_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
    from mmlspark_tpu.core.fusion import fuse

    failures = []
    for title, model, expected_ratio in build_pipelines():
        plan = plan_fusion(model.get("stages"))
        fused_t, staged_t = plan.transfers_per_batch()
        # runtime knobs come off the fused model the way serve_model would
        # build it — a segment that stopped donating (or lost its dispatch
        # pipeline) prints as donate=OFF / in_flight=1 right next to its
        # sharding spec, so the regression is visible in CI output
        fm = fuse(model, mesh=mesh)
        depth = fm.get("pipeline_depth")
        if depth is None:
            depth = fm.get("readback_lag")
        print(f"== {title} ==")
        desc = plan.describe(mesh=mesh, donate=fm.get("donate_buffers"),
                             pipeline_depth=depth)
        print(desc)
        print(f"   transfers/batch: fused={fused_t} staged={staged_t}")
        if plan.fusion_ratio < expected_ratio:
            failures.append(
                f"{title}: fusion ratio {plan.fusion_ratio:.2f} < "
                f"expected {expected_ratio:.2f}")
        # the GBDT segment must advertise the fused decode->bin->traverse
        # kernel — losing the label means the model kernel regressed to
        # an unlabeled (two-dispatch era) program
        if "gbdt" in title and "kernel=fused_traverse" not in desc:
            failures.append(
                f"{title}: describe() lacks kernel=fused_traverse — "
                "GBDT segment lost the fused inference kernel label")
        print()
    if failures:
        print("FUSION REPORT FAILURES:")
        for f in failures:
            print(f"  {f}")
        return 1
    print("fusion report ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
